"""Device-resident multi-target probe table (dprf_tpu/targets/):
planted hits at first/last/random indices across 10^3..10^5 target
counts, zero dropped and zero false hits after exact verify,
survivor-overflow redrive exactness, the HBM-budget host-verify
degrade, and the TargetStore ingest layer.

Early-alphabet filename on purpose: the tier-1 gate's wall clock cuts
the suite off mid-alphabet, and the probe plane must stay inside it.
"""

import random

import numpy as np
import pytest

pytestmark = pytest.mark.smoke

from dprf_tpu import get_engine
from dprf_tpu.engines.base import Target
from dprf_tpu.generators.mask import MaskGenerator
from dprf_tpu.runtime.workunit import WorkUnit
from dprf_tpu.targets import (MODE_DEVICE, MODE_HOST_VERIFY,
                              TargetStore, build_probe_table,
                              probe_eligible)


def _planted_targets(oracle, gen, n_targets: int, n_plants: int,
                     seed: int = 7):
    """n_targets synthetic digests with n_plants real ones planted at
    the FIRST, LAST, and random positions of the target list, hashing
    candidates at the FIRST, LAST, and random keyspace indices."""
    rng = random.Random(seed)
    cand_idx = [0, gen.keyspace - 1] + sorted(
        rng.sample(range(1, gen.keyspace - 1), n_plants - 2))
    plants = [gen.candidate(i) for i in cand_idx]
    digests = [rng.randbytes(16) for _ in range(n_targets)]
    positions = [0, n_targets - 1] + sorted(
        rng.sample(range(1, n_targets - 1), n_plants - 2))
    planted = {}
    for pos, plain in zip(positions, plants):
        digests[pos] = oracle.hash_batch([plain])[0]
        planted[pos] = plain
    targets = [Target(raw=f"t{i}", digest=d)
               for i, d in enumerate(digests)]
    return targets, planted


def _worker(targets, oracle, batch=256, **kw):
    from dprf_tpu.runtime.worker import DeviceMaskWorker
    gen = MaskGenerator("?d?d?d")
    dev = get_engine("md5", "jax")
    return DeviceMaskWorker(dev, gen, targets, batch=batch,
                            oracle=oracle, **kw), gen


@pytest.mark.parametrize("n_targets", [1_000, 10_000, 100_000])
def test_probe_planted_hits_exact(n_targets, monkeypatch):
    """Every planted hit recovered, nothing else reported -- the
    per-candidate cost of the step is independent of n_targets, so
    the same mask sweep covers every size."""
    monkeypatch.setenv("DPRF_TARGETS_PROBE_MIN", "100")
    oracle = get_engine("md5", "cpu")
    gen = MaskGenerator("?d?d?d")
    targets, planted = _planted_targets(oracle, gen, n_targets, 8)
    w, gen = _worker(targets, oracle)
    assert w.ATTACK == "mask+probe"   # the probe path, not the table
    hits = w.process(WorkUnit(0, 0, gen.keyspace))
    got = {h.target_index: h.plaintext for h in hits}
    assert got == planted             # zero dropped, zero false
    for h in hits:
        assert oracle.hash_batch([h.plaintext])[0] == \
            targets[h.target_index].digest


def test_probe_survivor_overflow_redrives_exactly(monkeypatch):
    """A survivor buffer smaller than one batch's true hit count
    inflates the step's count past capacity; the existing overflow
    rescan must recover every hit exactly (no dropped, no dupes)."""
    monkeypatch.setenv("DPRF_TARGETS_PROBE_MIN", "100")
    monkeypatch.setenv("DPRF_TARGETS_SURVIVOR_CAP", "4")
    oracle = get_engine("md5", "cpu")
    gen = MaskGenerator("?d?d?d")
    rng = random.Random(3)
    digests = [rng.randbytes(16) for _ in range(5_000)]
    # 12 planted hits inside the FIRST batch window (> the 4-slot
    # survivor buffer), plus a few spread across later batches
    planted_cands = list(range(12)) + [400, 700, 999]
    planted = {}
    for i, ci in enumerate(planted_cands):
        plain = gen.candidate(ci)
        pos = 17 * i + 3
        digests[pos] = oracle.hash_batch([plain])[0]
        planted[pos] = plain
    targets = [Target(raw=f"t{i}", digest=d)
               for i, d in enumerate(digests)]
    w, gen = _worker(targets, oracle)
    assert w.ATTACK == "mask+probe"
    hits = w.process(WorkUnit(0, 0, gen.keyspace))
    assert len(hits) == len(planted)  # exactness: no dupes either
    got = {h.target_index: h.plaintext for h in hits}
    assert got == planted


def test_probe_budget_degrades_to_host_verify(monkeypatch):
    """An HBM budget too small for the exact-verify table degrades to
    the documented host-verify layout (Bloom on device, oracle on
    host) instead of failing -- and still recovers every hit."""
    monkeypatch.setenv("DPRF_TARGETS_PROBE_MIN", "100")
    monkeypatch.setenv("DPRF_TARGETS_MAX_BYTES", "16384")
    oracle = get_engine("md5", "cpu")
    gen = MaskGenerator("?d?d?d")
    targets, planted = _planted_targets(oracle, gen, 20_000, 6,
                                        seed=11)
    pt = build_probe_table([t.digest for t in targets])
    assert pt.mode == MODE_HOST_VERIFY
    assert pt.nbytes <= 16384
    w, gen = _worker(targets, oracle)
    assert w.ATTACK == "mask+probe"
    hits = w.process(WorkUnit(0, 0, gen.keyspace))
    got = {h.target_index: h.plaintext for h in hits}
    assert got == planted


def test_probe_sharded_runtime_sentinel_path(monkeypatch):
    """The mesh runtime carries the probe table as replicated closure
    state; planted hits across shard boundaries come back exact."""
    monkeypatch.setenv("DPRF_TARGETS_PROBE_MIN", "100")
    from dprf_tpu.parallel.mesh import make_mesh
    from dprf_tpu.parallel.worker import ShardedMaskWorker
    oracle = get_engine("md5", "cpu")
    dev = get_engine("md5", "jax")
    gen = MaskGenerator("?d?d?d")
    targets, planted = _planted_targets(oracle, gen, 10_000, 6,
                                        seed=23)
    mesh = make_mesh(8)
    w = ShardedMaskWorker(dev, gen, targets, mesh, 128, oracle=oracle)
    assert w.ATTACK == "mask+probe"
    hits = w.process(WorkUnit(0, 0, gen.keyspace))
    got = {h.target_index: h.plaintext for h in hits}
    assert got == planted


def test_bloom_has_no_false_negatives():
    """Property: every member digest survives its own Bloom filter."""
    import jax.numpy as jnp

    from dprf_tpu.targets import bloom_maybe
    rng = random.Random(5)
    digests = [rng.randbytes(16) for _ in range(2_000)]
    pt = build_probe_table(digests)
    assert pt.mode == MODE_DEVICE
    rows = np.stack([np.frombuffer(d, dtype="<u4") for d in digests])
    maybe = np.asarray(bloom_maybe(
        jnp.asarray(rows.astype(np.uint32)), pt))
    assert maybe.all()
    assert 0.0 < pt.fp_est <= 1e-3


def test_probe_eligibility_gates():
    oracle = get_engine("md5", "cpu")
    few = [Target(raw="x", digest=bytes(16))] * 10
    assert not probe_eligible(few)                 # below the floor
    import os
    many = [Target(raw=f"t{i}", digest=os.urandom(16))
            for i in range(5_000)]
    assert probe_eligible(many, get_engine("md5", "jax"))
    assert oracle is not None


def test_target_store_ingest_report_and_fingerprint(tmp_path):
    oracle = get_engine("md5", "cpu")
    good = [oracle.hash_batch([f"pw{i}".encode()])[0].hex()
            for i in range(6)]
    lines = good + [good[0], "zz-not-a-digest", "", "# comment"]
    store = TargetStore.from_lines(oracle, lines, source="mem")
    assert len(store) == 6                    # deduped
    assert store.duplicates == 1
    assert [err for _no, _t, err in store.skipped]  # malformed logged
    rep = store.report()
    assert rep["targets"] == 6 and rep["duplicates"] == 1
    assert rep["malformed"] and rep["fingerprint"]
    # fingerprint: stable under reorder + dup, different on change
    shuffled = TargetStore.from_lines(oracle, list(reversed(good)))
    assert shuffled.fingerprint == store.fingerprint
    other = TargetStore.from_lines(oracle, good[:-1])
    assert other.fingerprint != store.fingerprint
    # file round-trip matches the in-memory parse
    p = tmp_path / "targets.txt"
    p.write_text("\n".join(lines) + "\n")
    on_disk = TargetStore.from_file(oracle, str(p))
    assert on_disk.fingerprint == store.fingerprint
    assert on_disk.lines() == store.lines()


def test_crack_cli_targets_file(tmp_path, capsys, monkeypatch):
    """`dprf crack --targets-file` end to end through the probe
    table: bulk list in, every planted plaintext out."""
    monkeypatch.setenv("DPRF_TARGETS_PROBE_MIN", "100")
    from dprf_tpu.cli import main
    oracle = get_engine("md5", "cpu")
    gen = MaskGenerator("?l?l?l")
    rng = random.Random(9)
    plants = [gen.candidate(i) for i in
              sorted(rng.sample(range(gen.keyspace), 10))]
    digests = [oracle.hash_batch([p])[0].hex() for p in plants]
    digests += [rng.randbytes(16).hex() for _ in range(4_000)]
    rng.shuffle(digests)
    tf = tmp_path / "bulk.txt"
    tf.write_text("\n".join(digests) + "\n")
    rc = main(["crack", "?l?l?l", "--targets-file", str(tf),
               "--engine", "md5", "--device", "tpu", "--no-potfile",
               "--unit-size", "8192", "--batch", "2048", "-q"])
    out = capsys.readouterr().out
    assert rc == 0
    lines = dict(ln.split(":", 1) for ln in out.strip().splitlines())
    assert len(lines) == 10
    for p in plants:
        assert lines[oracle.hash_batch([p])[0].hex()] == p.decode()
