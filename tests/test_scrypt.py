"""scrypt: device pipeline vs hashlib.scrypt (RFC 7914 vectors by
construction), the engine's parse/oracle, and worker cracks with small
N/r/p so the CPU-mesh suite stays fast."""

import base64
import hashlib

import numpy as np
import pytest

# device-pipeline compiles: full suite / tier-1, excluded from the <5-min
# smoke tier (tools/check_markers.py enforces an explicit tier decision)
pytestmark = pytest.mark.compileheavy

from dprf_tpu.engines import get_engine
from dprf_tpu.generators.mask import MaskGenerator
from dprf_tpu.generators.wordlist import WordlistRulesGenerator
from dprf_tpu.runtime.workunit import WorkUnit


def _line(pw: bytes, salt: bytes, n: int, r: int, p: int) -> str:
    dk = hashlib.scrypt(pw, salt=salt, n=n, r=r, p=p, dklen=32,
                        maxmem=1 << 27)
    return "SCRYPT:%d:%d:%d:%s:%s" % (
        n, r, p, base64.b64encode(salt).decode(),
        base64.b64encode(dk).decode())


@pytest.mark.parametrize("n,r,p", [(16, 1, 1), (8, 2, 2), (32, 4, 1)])
def test_scrypt_dk_matches_hashlib(n, r, p):
    import jax.numpy as jnp

    from dprf_tpu.ops.hmac import pack_raw_varlen
    from dprf_tpu.ops.scrypt import scrypt_dk

    pws = [b"pleaseletmein", b"", b"pw0123456789"]
    buf = np.zeros((len(pws), 64), np.uint8)
    lens = []
    for i, c in enumerate(pws):
        buf[i, :len(c)] = np.frombuffer(c, np.uint8)
        lens.append(len(c))
    kw = pack_raw_varlen(jnp.asarray(buf), jnp.asarray(lens, jnp.int32),
                         True)
    salt = b"SodiumChloride"
    sbuf = np.zeros(51, np.uint8)
    sbuf[:len(salt)] = np.frombuffer(salt, np.uint8)
    dk = np.asarray(scrypt_dk(kw, jnp.asarray(sbuf),
                              jnp.int32(len(salt)), n, r, p))
    for i, c in enumerate(pws):
        want = np.frombuffer(
            hashlib.scrypt(c, salt=salt, n=n, r=r, p=p, dklen=32,
                           maxmem=1 << 27), ">u4")
        assert (dk[i] == want).all(), (n, r, p, c)


def test_parse_and_oracle():
    eng = get_engine("scrypt")
    t = eng.parse_target(_line(b"password", b"NaCl", 16, 8, 1))
    assert (t.params["n"], t.params["r"], t.params["p"]) == (16, 8, 1)
    assert eng.hash_batch([b"password"], params=t.params)[0] == t.digest
    with pytest.raises(ValueError):
        eng.parse_target("SCRYPT:15:8:1:AA==:AA==")   # N not a power of 2
    with pytest.raises(ValueError):
        eng.parse_target("nonsense")


def test_device_mask_worker_cracks():
    cpu = get_engine("scrypt")
    dev = get_engine("scrypt", device="jax")
    gen = MaskGenerator("?l?l?l")
    t = cpu.parse_target(_line(b"fox", b"pepper", 16, 1, 1))
    w = dev.make_mask_worker(gen, [t], batch=512, hit_capacity=8,
                             oracle=cpu)
    hits = w.process(WorkUnit(0, 0, gen.keyspace))
    assert [h.plaintext for h in hits] == [b"fox"]


def test_device_mixed_params_two_targets():
    """Targets with different (N, r, p) share a worker; steps are
    compiled per parameter tuple."""
    cpu = get_engine("scrypt")
    dev = get_engine("scrypt", device="jax")
    gen = MaskGenerator("?d?d")
    ta = cpu.parse_target(_line(b"42", b"saltA", 16, 1, 1))
    tb = cpu.parse_target(_line(b"77", b"saltB", 8, 2, 1))
    w = dev.make_mask_worker(gen, [ta, tb], batch=128, hit_capacity=8,
                             oracle=cpu)
    hits = w.process(WorkUnit(0, 0, gen.keyspace))
    assert {(h.target_index, h.plaintext) for h in hits} == \
        {(0, b"42"), (1, b"77")}


def test_device_wordlist_worker_cracks():
    from dprf_tpu.rules.parser import parse_rule

    cpu = get_engine("scrypt")
    dev = get_engine("scrypt", device="jax")
    gen = WordlistRulesGenerator(
        words=[b"apple", b"Banana", b"zebra"],
        rules=[parse_rule(":"), parse_rule("l")])
    t = cpu.parse_target(_line(b"banana", b"s4lt", 16, 1, 1))
    w = dev.make_wordlist_worker(gen, [t], batch=64, hit_capacity=8,
                                 oracle=cpu)
    hits = w.process(WorkUnit(0, 0, gen.keyspace))
    assert b"banana" in {h.plaintext for h in hits}


def test_sharded_mask_worker_cracks():
    from dprf_tpu.parallel import make_mesh

    cpu = get_engine("scrypt")
    dev = get_engine("scrypt", device="jax")
    gen = MaskGenerator("?l?l?l")
    t = cpu.parse_target(_line(b"dog", b"m", 8, 1, 1))
    w = dev.make_sharded_mask_worker(gen, [t], make_mesh(8),
                                     batch_per_device=64,
                                     hit_capacity=8, oracle=cpu)
    hits = w.process(WorkUnit(0, 0, gen.keyspace))
    assert [h.plaintext for h in hits] == [b"dog"]


def test_batch_clamped_to_memory_cap(monkeypatch):
    monkeypatch.setenv("DPRF_SCRYPT_MEM", str(1 << 20))   # 1 MiB cap
    cpu = get_engine("scrypt")
    dev = get_engine("scrypt", device="jax")
    gen = MaskGenerator("?d?d")
    t = cpu.parse_target(_line(b"11", b"s", 64, 1, 1))    # 8 KiB/cand
    w = dev.make_mask_worker(gen, [t], batch=1 << 16, hit_capacity=8,
                             oracle=cpu)
    assert w.batch == (1 << 20) // (128 * 64)
    hits = w.process(WorkUnit(0, 0, gen.keyspace))
    assert [h.plaintext for h in hits] == [b"11"]


def test_parse_rejects_huge_n():
    eng = get_engine("scrypt")
    with pytest.raises(ValueError):
        eng.parse_target("SCRYPT:33554432:8:1:AA==:" +
                         base64.b64encode(bytes(32)).decode())


def test_wordlist_rejects_rules_over_memory_budget(monkeypatch):
    from dprf_tpu.rules.parser import parse_rule

    monkeypatch.setenv("DPRF_SCRYPT_MEM", str(1 << 16))   # 64 KiB
    cpu = get_engine("scrypt")
    dev = get_engine("scrypt", device="jax")
    # 64 KiB / (128*16) = 32 candidates max; 40 rules can't fit
    gen = WordlistRulesGenerator(
        words=[b"a"], rules=[parse_rule(f"${c}") for c in
                             "abcdefghijklmnopqrstuvwxyz0123456789!@#$"])
    t = cpu.parse_target(_line(b"x", b"s", 16, 1, 1))
    with pytest.raises(ValueError, match="memory budget"):
        dev.make_wordlist_worker(gen, [t], batch=1 << 10,
                                 hit_capacity=8, oracle=cpu)
