"""Wide-step dispatch (MaskWorkerBase.SUPER_MODE == "wide"): Pallas
workers fuse multi-batch WorkUnits by rebuilding their own step at
inner*stride lanes -- the same single-pallas_call program shape as a
plain batch, with a longer (sequential) grid -- instead of
scan-wrapping the step (ops/superstep.py), which wedged the axon TPU
backend's remote compile helper (TPU_PROBE_LOG_r04.md, round-4b
finding).  These tests pin: wide == per-batch bit-identical hits
(single target, multi target, wordlist+rules), window-sized overflow
rescan, capacity scaling, and per-batch degradation when the wide
program fails to build.
"""

import hashlib

import numpy as np
import pytest

jnp = pytest.importorskip("jax.numpy")

from dprf_tpu import get_engine
from dprf_tpu.generators.mask import MaskGenerator
from dprf_tpu.generators.wordlist import WordlistRulesGenerator
from dprf_tpu.ops.pallas_mask import TILE
from dprf_tpu.runtime.worker import PallasMaskWorker, PallasWordlistWorker
from dprf_tpu.runtime.workunit import WorkUnit
from dprf_tpu.rules.parser import parse_rule

pytestmark = pytest.mark.smoke


@pytest.fixture(scope="module")
def md5_jax():
    return get_engine("md5", device="jax")


def _hits(hits):
    return sorted((h.target_index, h.cand_index, h.plaintext)
                  for h in hits)


def _tgts(eng, plants):
    return [eng.parse_target(hashlib.md5(p).hexdigest()) for p in plants]


def _pallas_worker(eng, gen, targets, **kw):
    kw.setdefault("batch", TILE)
    kw.setdefault("oracle", get_engine("md5"))
    return PallasMaskWorker(eng, gen, targets, interpret=True, **kw)


@pytest.mark.parametrize("plant_idx", [8 * TILE - 1,   # last wide lane
                                       8 * TILE + 5])  # per-batch tail
def test_wide_single_matches_per_batch(md5_jax, monkeypatch, plant_idx):
    """12 strides: one wide chunk of 8 + per-batch tail of 4
    (SUPER_MIN = 8); hits at the wide/tail boundary must decode to the
    same global indices on both paths."""
    gen = MaskGenerator("?l?l?l?l")
    unit = WorkUnit(0, 0, 12 * TILE)
    plant = gen.candidate(plant_idx)
    w = _pallas_worker(md5_jax, gen, _tgts(md5_jax, [plant]))
    got = _hits(w.process(unit))
    assert got == [(0, plant_idx, plant)]
    assert any(k > TILE for k in getattr(w, "_wide_cache", {})), \
        "wide dispatch never engaged"
    monkeypatch.setenv("DPRF_SUPERSTEP", "0")
    w2 = _pallas_worker(md5_jax, gen, _tgts(md5_jax, [plant]))
    assert got == _hits(w2.process(unit))
    assert not getattr(w2, "_wide_cache", {})


def test_wide_multi_target_matches_per_batch(md5_jax, monkeypatch):
    """Bloom multi-target kernel through the wide path: maybes verify
    against the oracle exactly as per-batch."""
    gen = MaskGenerator("?l?l?l?l")
    plants = [gen.candidate(3), gen.candidate(5 * TILE + 77),
              gen.candidate(9 * TILE + 1)]
    targets = _tgts(md5_jax, plants) + _tgts(md5_jax, [b"zzzz"])
    unit = WorkUnit(0, 0, 12 * TILE)
    w = _pallas_worker(md5_jax, gen, targets)
    got = _hits(w.process(unit))
    assert {h[2] for h in got} == set(plants)
    monkeypatch.setenv("DPRF_SUPERSTEP", "0")
    w2 = _pallas_worker(md5_jax, gen, targets)
    assert got == _hits(w2.process(unit))


def test_wide_offset_unit(md5_jax):
    """Wide chunks of a unit not starting at 0 decode global indices
    from the chunk base, not the unit base."""
    gen = MaskGenerator("?l?l?l?l")
    start = 2 * TILE + 31
    unit = WorkUnit(1, start, 10 * TILE)
    plant_idx = start + 7 * TILE + 11
    plant = gen.candidate(plant_idx)
    w = _pallas_worker(md5_jax, gen, _tgts(md5_jax, [plant]))
    assert _hits(w.process(unit)) == [(0, plant_idx, plant)]


def test_wide_overflow_redrives_per_batch(md5_jax):
    """A wide result whose count exceeds its (scaled) buffer re-runs
    the window through the per-batch DEVICE step (collision sentinels
    fire on any two-hit tile, so wide overflow must not mean a
    whole-window host rescan) -- and still finds hits anywhere in the
    window."""
    gen = MaskGenerator("?l?l?l?l")
    plant_idx = 3 * TILE + 123           # beyond the first stride
    plant = gen.candidate(plant_idx)
    # no oracle: a host rescan would raise; the device redrive must not
    w = PallasMaskWorker(md5_jax, gen, _tgts(md5_jax, [plant]),
                         batch=TILE, oracle=None, interpret=True)
    unit = WorkUnit(0, 0, 8 * TILE)
    fake = (np.int32(9999), np.full((4,), -1, np.int32),
            np.zeros((4,), np.int32))
    hits = w._batch_hits(0, fake, unit, window=8 * TILE)
    assert _hits(hits) == [(0, plant_idx, plant)]


def test_wordlist_wide_overflow_redrives_per_batch():
    """Same for the rules kernel: an overflowed wide word window
    re-runs per word_batch on device, decoding with the per-batch
    lane stride."""
    from dprf_tpu.ops.pallas_rules import TILE_W

    eng = get_engine("md5", device="jax")
    words = [b"w%06d" % i for i in range(4 * TILE_W)]
    rules = [parse_rule(":"), parse_rule("u")]
    gen = WordlistRulesGenerator(words, rules, max_len=16)
    wi = 2 * TILE_W + 17
    plant = words[wi].upper()
    targets = [get_engine("md5").parse_target(
        hashlib.md5(plant).hexdigest())]
    w = PallasWordlistWorker(eng, gen, targets,
                             batch=TILE_W * gen.n_rules,
                             oracle=None, interpret=True)
    unit = WorkUnit(0, 0, gen.keyspace)
    fake = (np.int32(9999), np.full((4,), -1, np.int32),
            np.zeros((4,), np.int32))
    hits = w._window_hits(0, 4 * TILE_W, fake, unit,
                          lane_wb=4 * TILE_W)
    assert _hits(hits) == [(0, wi * gen.n_rules + 1, plant)]


def test_wordlist_wide_shared_eviction():
    """Building a wide size whose window outgrows the shared arrays'
    padding rebuilds+replaces them and evicts cached steps holding
    the old copy (at most one wide wordlist copy in HBM)."""
    from dprf_tpu.ops.pallas_rules import TILE_W

    eng = get_engine("md5", device="jax")
    words = [b"q%06d" % i for i in range(8 * TILE_W)]
    rules = [parse_rule(":"), parse_rule("u")]
    gen = WordlistRulesGenerator(words, rules, max_len=16)
    targets = [get_engine("md5").parse_target("ff" * 16)]
    w = PallasWordlistWorker(eng, gen, targets,
                             batch=TILE_W * gen.n_rules,
                             oracle=None, interpret=True)
    s1 = w._wide_step(2 * TILE_W)
    assert 2 * TILE_W in w._wide_cache
    s2 = w._wide_step(8 * TILE_W)    # outgrows s1's padding
    assert s2.words4 is not s1.words4
    assert 2 * TILE_W not in w._wide_cache, "stale copy not evicted"
    assert w._wide_cache[8 * TILE_W] is s2
    s3 = w._wide_step(4 * TILE_W)    # fits s2's padding: reuses
    assert s3.words4 is s2.words4


def test_wide_capacity_scales_with_inner(md5_jax):
    """hit_capacity=1 per batch would overflow on >1 hit per window;
    the wide step's scaled buffer holds one hit per stride without a
    rescan (no oracle provided -- a rescan would raise)."""
    gen = MaskGenerator("?l?l?l?l")
    plants = [gen.candidate(i * TILE + i) for i in range(4)]
    # single-target kernel: sweep one plant per worker, no oracle
    for i, p in enumerate(plants):
        w = PallasMaskWorker(md5_jax, gen, _tgts(md5_jax, [p]),
                             batch=TILE, hit_capacity=1, oracle=None,
                             interpret=True)
        got = _hits(w.process(WorkUnit(0, 0, 8 * TILE)))
        assert got == [(0, i * TILE + i, p)]


def test_wide_build_failure_degrades_to_per_batch(md5_jax):
    gen = MaskGenerator("?l?l?l?l")
    plant = gen.candidate(9 * TILE + 9)
    w = _pallas_worker(md5_jax, gen, _tgts(md5_jax, [plant]))

    def boom(batch):
        raise RuntimeError("no wide program on this backend")

    w._make_step = boom
    got = _hits(w.process(WorkUnit(0, 0, 12 * TILE)))
    assert got == [(0, 9 * TILE + 9, plant)]
    assert w._wide_disabled
    # subsequent units stay per-batch: NEVER the scan wrapper, which
    # is the shape that wedges the axon compile helper
    got2 = _hits(w.process(WorkUnit(1, 0, 12 * TILE)))
    assert got2 == got
    assert not getattr(w, "_super_cache", None)


@pytest.mark.compileheavy    # interpret-mode rules-kernel wide build
def test_wordlist_wide_matches_per_batch(monkeypatch):
    """PallasWordlistWorker wide dispatch: flat rule-major lanes are
    decoded with the WIDE word stride (lane = r * n_words + b), so a
    hit deep in the window must map to the right (word, rule)."""
    from dprf_tpu.ops.pallas_rules import TILE_W

    eng = get_engine("md5", device="jax")
    cpu = get_engine("md5")
    rng = np.random.default_rng(11)
    alpha = np.frombuffer(b"abcdefghijklmnopqrstuvwxyz", np.uint8)
    words = [bytes(alpha[rng.integers(0, 26, 6)])
             for _ in range(8 * TILE_W)]
    rules = [parse_rule(":"), parse_rule("u")]
    gen = WordlistRulesGenerator(words, rules, max_len=16)
    wi = 5 * TILE_W + 321
    plant = words[wi].upper()              # rule 1 on word wi
    targets = [cpu.parse_target(hashlib.md5(plant).hexdigest())]
    w = PallasWordlistWorker(eng, gen, targets,
                             batch=TILE_W * gen.n_rules,
                             oracle=cpu, interpret=True)
    unit = WorkUnit(0, 0, gen.keyspace)
    got = _hits(w.process(unit))
    assert got == [(0, wi * gen.n_rules + 1, plant)]
    assert any(k > TILE_W for k in getattr(w, "_wide_cache", {})), \
        "wordlist wide dispatch never engaged"
    monkeypatch.setenv("DPRF_SUPERSTEP", "0")
    w2 = PallasWordlistWorker(eng, gen, targets,
                              batch=TILE_W * gen.n_rules,
                              oracle=cpu, interpret=True)
    assert got == _hits(w2.process(unit))
    # all wide sizes share ONE device copy of the packed wordlist
    # (built at the largest window; narrower windows reuse it)
    s_big = w._wide_cache[8 * TILE_W]
    s_small = w._make_step(4 * TILE_W)
    assert s_small.words4 is s_big.words4
    assert s_small.lens3 is s_big.lens3


def test_salted_wide_matches_per_batch(monkeypatch):
    """PallasSaltedMaskWorker fuses its per-target sweep into wide
    kernel dispatches; hits and indices must match the per-batch path
    and the wide kernels must actually be built."""
    from dprf_tpu.engines.device.salted import PallasSaltedMaskWorker

    monkeypatch.setenv("DPRF_PALLAS", "1")
    gen = MaskGenerator("?l?l?l?l")
    cpu = get_engine("md5-ps", device="cpu")
    dev = get_engine("md5-ps", device="jax")
    plants = [(8 * TILE - 1, b"na"), (9 * TILE + 5, b"clsalt")]
    targets = []
    for idx, salt in plants:
        d = cpu.hash_batch([gen.candidate(idx)],
                           params={"salt": salt})[0]
        targets.append(cpu.parse_target(d.hex() + ":" + salt.decode()))
    unit = WorkUnit(0, 0, 12 * TILE)
    w = dev.make_mask_worker(gen, targets, batch=TILE,
                             hit_capacity=8, oracle=cpu)
    assert isinstance(w, PallasSaltedMaskWorker)
    got = _hits(w.process(unit))
    assert {(t, i) for t, i, _ in got} == {(0, 8 * TILE - 1),
                                          (1, 9 * TILE + 5)}
    assert any(sb > TILE for _, sb in w._wide_ksteps), \
        "wide salted kernels never engaged"
    monkeypatch.setenv("DPRF_SUPERSTEP", "0")
    w2 = dev.make_mask_worker(gen, targets, batch=TILE,
                              hit_capacity=8, oracle=cpu)
    assert got == _hits(w2.process(unit))
    assert not w2._wide_ksteps
