"""Rule engine tests: parser, CPU oracle, CPU==device equivalence, the
fused wordlist+rules pipeline, and the sharded variant (config 3).

The equivalence test is the load-bearing one (SURVEY.md section 4:
"rule engine vs a Python rule interpreter oracle"): every opcode is
exercised on a word set chosen to hit the no-op / reject / overflow
edges, and the device batch application must agree byte-for-byte.
"""

import hashlib
import random

import numpy as np
import pytest

pytestmark = pytest.mark.smoke

import jax.numpy as jnp

from dprf_tpu.rules import (parse_rule, parse_rules, load_rules,
                            apply_rule_cpu)
from dprf_tpu.rules.device import apply_rule as apply_rule_dev
from dprf_tpu.rules.parser import Op, Opcode
from dprf_tpu.generators.wordlist import WordlistRulesGenerator, NOOP_RULE


# ---------------------------------------------------------------- parser

def test_parse_simple_ops():
    assert parse_rule(":") == (Op(Opcode.NOOP),)
    assert parse_rule("l") == (Op(Opcode.LOWER),)
    assert parse_rule("$1") == (Op(Opcode.APPEND, ord("1")),)
    assert parse_rule("^a") == (Op(Opcode.PREPEND, ord("a")),)
    assert parse_rule("sa@") == (Op(Opcode.SUBSTITUTE, ord("a"), ord("@")),)
    assert parse_rule("T3") == (Op(Opcode.TOGGLE_AT, 3),)
    assert parse_rule("TA") == (Op(Opcode.TOGGLE_AT, 10),)
    assert parse_rule("x04") == (Op(Opcode.EXTRACT, 0, 4),)
    assert parse_rule("i2!") == (Op(Opcode.INSERT, 2, ord("!")),)


def test_parse_multi_op_rule_with_spaces():
    ops = parse_rule("c se3 $1 $2")
    assert [o.opcode for o in ops] == [
        Opcode.CAPITALIZE, Opcode.SUBSTITUTE, Opcode.APPEND, Opcode.APPEND]


def test_parse_space_as_char_param():
    # '$ ' appends a space: space is a parameter here, not a separator.
    assert parse_rule("$ ") == (Op(Opcode.APPEND, 0x20),)


def test_parse_errors():
    with pytest.raises(ValueError):
        parse_rule("~")            # unknown op
    with pytest.raises(ValueError):
        parse_rule("T")            # missing param
    with pytest.raises(ValueError):
        parse_rule("Tz")           # bad position digit
    with pytest.raises(ValueError):
        parse_rule("")


def test_parse_rules_skip_mode():
    rules = parse_rules([":", "# comment", "", "~bogus", "u"],
                        on_error="skip")
    assert len(rules) == 2


def test_builtin_rulesets_load():
    for name in ("best64", "leetspeak", "toggle"):
        rules = load_rules(name)
        assert len(rules) >= 16
    assert len(load_rules("best64")) == 64


# ------------------------------------------------------------ CPU oracle

CASES = [
    (b"password", ":", b"password"),
    (b"PassWord", "l", b"password"),
    (b"password", "u", b"PASSWORD"),
    (b"pASSWORD", "c", b"Password"),
    (b"Password", "C", b"pASSWORD"),
    (b"PaSsWoRd", "t", b"pAsSwOrD"),
    (b"password", "T0", b"Password"),
    (b"password", "T8", b"password"),      # out of range: no-op
    (b"password", "r", b"drowssap"),
    (b"pass", "d", b"passpass"),
    (b"pass", "p2", b"passpasspass"),
    (b"pass", "f", b"passssap"),
    (b"password", "{", b"asswordp"),
    (b"password", "}", b"dpasswor"),
    (b"password", "[", b"assword"),
    (b"password", "]", b"passwor"),
    (b"password", "D3", b"pasword"),
    (b"password", "x04", b"pass"),
    (b"password", "x45", b"word"),
    (b"password", "O24", b"pard"),
    (b"password", "i2XY", None),           # parse err tested elsewhere
    (b"password", "'4", b"pass"),
    (b"password", "sa@", b"p@ssword"),
    (b"password", "@s", b"paword"),
    (b"pass", "z2", b"pppass"),
    (b"pass", "Z2", b"passss"),
    (b"ab", "q", b"aabb"),
    (b"password", "k", b"apssword"),
    (b"password", "K", b"passwodr"),
    (b"password", "*07", b"dasswor" + b"p"),
    (b"password", "+0", b"qassword"),
    (b"password", "-0", b"oassword"),
    (b"password", ".1", b"psssword"),
    (b"password", ",1", b"ppssword"),
    (b"password", "y2", b"papassword"),
    (b"password", "Y2", b"passwordrd"),
    (b"pass", "$1", b"pass1"),
    (b"pass", "^1", b"1pass"),
    (b"john smith", "E", b"John Smith"),
    (b"john-smith", "e-", b"John-Smith"),
    (b"pass", "i4!", b"pass!"),
    (b"pass", "i9!", b"pass"),             # out of range: no-op
    (b"pass", "o0P", b"Pass"),
    (b"pass", "o9P", b"pass"),
]


@pytest.mark.parametrize("word,rule,want", CASES)
def test_cpu_known_values(word, rule, want):
    if want is None:
        return
    ops = parse_rule(rule)
    assert apply_rule_cpu(word, ops, max_len=16) == want


def test_cpu_reject_semantics():
    assert apply_rule_cpu(b"longishword", parse_rule("d"), max_len=16) is None
    assert apply_rule_cpu(b"pass", parse_rule("<3")) is None
    assert apply_rule_cpu(b"pass", parse_rule("<4")) == b"pass"
    assert apply_rule_cpu(b"pass", parse_rule(">5")) is None
    assert apply_rule_cpu(b"pass", parse_rule(">4")) == b"pass"
    assert apply_rule_cpu(b"pass", parse_rule("_4")) == b"pass"
    assert apply_rule_cpu(b"pass", parse_rule("_5")) is None
    assert apply_rule_cpu(b"pass", parse_rule("!a")) is None
    assert apply_rule_cpu(b"pass", parse_rule("!z")) == b"pass"
    assert apply_rule_cpu(b"pass", parse_rule("/q")) is None
    assert apply_rule_cpu(b"pass", parse_rule("/s")) == b"pass"
    assert apply_rule_cpu(b"pass", parse_rule("(p")) == b"pass"
    assert apply_rule_cpu(b"pass", parse_rule("(a")) is None
    assert apply_rule_cpu(b"pass", parse_rule(")s")) == b"pass"
    assert apply_rule_cpu(b"pass", parse_rule(")p")) is None
    assert apply_rule_cpu(b"pass", parse_rule("=1a")) == b"pass"
    assert apply_rule_cpu(b"pass", parse_rule("=0a")) is None
    assert apply_rule_cpu(b"pass", parse_rule("%2s")) == b"pass"
    assert apply_rule_cpu(b"pass", parse_rule("%3s")) is None


def test_cpu_append_overflow_rejects():
    assert apply_rule_cpu(b"a" * 16, parse_rule("$1"), max_len=16) is None
    assert apply_rule_cpu(b"a" * 15, parse_rule("$1"), max_len=16) == \
        b"a" * 15 + b"1"


# ------------------------------------------------- CPU == device property

# One rule per opcode (several for the parameterized ones), chosen to
# hit in-range, out-of-range, and overflow behavior on the word set.
EQUIV_RULES = [
    ":", "l", "u", "c", "C", "t", "T0", "T2", "T9", "TZ", "r",
    "d", "p1", "p3", "f", "{", "}", "[", "]", "D0", "D4", "DZ",
    "x02", "x25", "x90", "O13", "O05", "OZ1",
    "i0^", "i3!", "i9#", "iZ@", "o0X", "o5Y", "oZ!",
    "'0", "'3", "'Z", "sa@", "se3", "sss", "@a", "@z",
    "z1", "z3", "Z1", "Z4", "q", "k", "K", "*05", "*50", "*28",
    "L0", "L3", "R0", "R3", "+1", "-1", ".0", ".5", ",1", ",5",
    "y2", "y5", "Y2", "Y5", "$1", "$ ", "^0", "^ ", "E", "e-", "e ",
    "<5", "<9", ">3", ">7", "_4", "_6", "!a", "!q", "/a", "/q",
    "(a", "(m", ")e", ")z", "=2s", "=9x", "%1a", "%2a", "%3a",
    # multi-op rules: interactions and ordering
    "c $1 $2", "u r", "d r ]", "f '6", "se3 sa@ so0", "l { } k",
    "^x ^y $z", "r r", "t T0 T0", "[ [ [", "q d",
]

WORDS = [b"", b"a", b"ab", b"abc", b"Passw0rd", b"aaaa", b"MIXEDcase",
         b"a b c", b"zzzzzzzzz", b"0123456789", b"sassafras",
         b"Aa!Bb?Cc", b"mmmmmmmmmmmm", b"x" * 16, b"e3e3e3",
         b"  lead", b"trail  ", b"@#$%^&*()", b"QqQqQq", b"longestwordhere!"]

MAXLEN = 16


def test_device_matches_cpu_all_ops():
    rules = [parse_rule(r) for r in EQUIV_RULES]
    B = len(WORDS)
    buf = np.zeros((B, MAXLEN), dtype=np.uint8)
    lens = np.zeros((B,), dtype=np.int32)
    for i, w in enumerate(WORDS):
        buf[i, :len(w)] = np.frombuffer(w, dtype=np.uint8)
        lens[i] = len(w)
    w_dev = jnp.asarray(buf)
    l_dev = jnp.asarray(lens)
    v_dev = jnp.ones((B,), dtype=bool)

    for rtext, ops in zip(EQUIV_RULES, rules):
        out_w, out_l, out_v = apply_rule_dev(w_dev, l_dev, v_dev, ops,
                                             MAXLEN)
        out_w, out_l, out_v = (np.asarray(out_w), np.asarray(out_l),
                               np.asarray(out_v))
        for i, word in enumerate(WORDS):
            want = apply_rule_cpu(word, ops, max_len=MAXLEN)
            got_valid = bool(out_v[i])
            if want is None:
                assert not got_valid, (
                    f"rule {rtext!r} word {word!r}: device accepted, "
                    f"oracle rejected")
            else:
                assert got_valid, (
                    f"rule {rtext!r} word {word!r}: device rejected, "
                    f"oracle gave {want!r}")
                got = bytes(out_w[i, :out_l[i]])
                assert got == want, (
                    f"rule {rtext!r} word {word!r}: device {got!r} "
                    f"!= oracle {want!r}")
                # zero-tail invariant
                assert not out_w[i, out_l[i]:].any()


def test_device_matches_cpu_random_fuzz():
    rng = random.Random(20260729)
    charset = (b"abcdefghijklmnopqrstuvwxyz"
               b"ABCDEFGHIJKLMNOPQRSTUVWXYZ0123456789 !@#$")
    words = [bytes(rng.choice(charset) for _ in range(rng.randrange(0, 13)))
             for _ in range(64)]
    rule_pool = [parse_rule(r) for r in EQUIV_RULES]
    B = len(words)
    buf = np.zeros((B, MAXLEN), dtype=np.uint8)
    lens = np.zeros((B,), dtype=np.int32)
    for i, w in enumerate(words):
        buf[i, :len(w)] = np.frombuffer(w, dtype=np.uint8)
        lens[i] = len(w)
    w_dev, l_dev = jnp.asarray(buf), jnp.asarray(lens)
    v_dev = jnp.ones((B,), dtype=bool)

    for _ in range(20):
        ops = tuple(op for r in rng.sample(rule_pool, rng.randrange(1, 4))
                    for op in r)
        out_w, out_l, out_v = map(np.asarray,
                                  apply_rule_dev(w_dev, l_dev, v_dev, ops,
                                                 MAXLEN))
        for i, word in enumerate(words):
            want = apply_rule_cpu(word, ops, max_len=MAXLEN)
            if want is None:
                assert not out_v[i]
            else:
                assert out_v[i]
                assert bytes(out_w[i, :out_l[i]]) == want


# ----------------------------------------------------------- generator

def test_wordlist_generator_keyspace_and_decode():
    words = [b"alpha", b"beta", b"gamma"]
    rules = [parse_rule(":"), parse_rule("u"), parse_rule("$1")]
    gen = WordlistRulesGenerator(words, rules, max_len=16)
    assert gen.keyspace == 9
    assert gen.candidate(0) == b"alpha"
    assert gen.candidate(1) == b"ALPHA"
    assert gen.candidate(2) == b"alpha1"
    assert gen.candidate(4) == b"BETA"
    assert gen.candidate(8) == b"gamma1"
    with pytest.raises(IndexError):
        gen.candidate(9)


def test_wordlist_generator_holes():
    gen = WordlistRulesGenerator([b"abcdefgh"], [parse_rule("d")],
                                 max_len=10)
    assert gen.candidate(0) is None        # 16 > 10: rejected
    assert gen.candidates(0, 1) == [None]


def test_load_words(tmp_path):
    from dprf_tpu.generators.wordlist import load_words
    p = tmp_path / "wl.txt"
    p.write_bytes(b"one\r\ntwo\n\nthree\n" + b"x" * 99 + b"\n")
    words, skipped = load_words(str(p), max_len=16)
    assert words == [b"one", b"two", b"three"]
    assert skipped == 1


# --------------------------------------------------- fused pipeline e2e

def _plant_step_test(engine_name, hash_fn, widen=False):
    from dprf_tpu.engines import get_engine
    from dprf_tpu.ops import compare as cmp_ops
    from dprf_tpu.ops.rules_pipeline import make_wordlist_crack_step

    words = [b"winter", b"dragon", b"secret", b"letmein", b"monkey",
             b"shadow", b"master", b"qwerty"]
    rules = [parse_rule(r) for r in (":", "c", "u", "$1", "c $1", "se3")]
    gen = WordlistRulesGenerator(words, rules, max_len=16)

    # Plant: "Dragon1" = word 1 via rule "c $1" (index 1*6+4), and
    # "s3cr3t" = word 2 via rule "se3" (index 2*6+5).
    plants = {1 * 6 + 4: b"Dragon1", 2 * 6 + 5: b"s3cr3t"}
    for idx, plain in plants.items():
        assert gen.candidate(idx) == plain
    table = cmp_ops.make_target_table(
        [hash_fn(p) for p in plants.values()],
        little_endian=get_engine(engine_name, device="jax").little_endian)

    engine = get_engine(engine_name, device="jax")
    step = make_wordlist_crack_step(engine, gen, table, word_batch=8,
                                    hit_capacity=8, widen_utf16=widen)
    count, lanes, tpos = step(jnp.int32(0), jnp.int32(len(words)))
    assert int(count) == 2
    got = set()
    for lane in np.asarray(lanes):
        if lane < 0:
            continue
        r, b = divmod(int(lane), 8)
        got.add(b * 6 + r)
    assert got == set(plants)


def test_pipeline_md5_wordlist_rules():
    _plant_step_test("md5", lambda p: hashlib.md5(p).digest())


def test_pipeline_sha256_wordlist_rules():
    # Benchmark config 3: SHA-256 raw, wordlist + rules.
    _plant_step_test("sha256", lambda p: hashlib.sha256(p).digest())


def test_pipeline_ntlm_wordlist_rules():
    from dprf_tpu.engines.cpu.md4 import md4

    def ntlm(pw):
        return md4(bytes(b for ch in pw for b in (ch, 0)))
    _plant_step_test("ntlm", ntlm, widen=True)


def test_worker_and_noop_wordlist():
    """Whole-worker path: wordlist only (NOOP rule), planted word."""
    from dprf_tpu.engines import get_engine
    from dprf_tpu.runtime.worker import DeviceWordlistWorker
    from dprf_tpu.runtime.workunit import WorkUnit
    from dprf_tpu.engines.base import Target

    words = [f"word{i:04d}".encode() for i in range(500)]
    words[321] = b"hunter2"
    gen = WordlistRulesGenerator(words, None, max_len=16)
    target = Target(raw=hashlib.md5(b"hunter2").hexdigest(),
                    digest=hashlib.md5(b"hunter2").digest())
    engine = get_engine("md5", device="jax")
    worker = DeviceWordlistWorker(engine, gen, [target], batch=64,
                                  hit_capacity=8,
                                  oracle=get_engine("md5", device="cpu"))
    hits = worker.process(WorkUnit(0, 0, gen.keyspace))
    assert len(hits) == 1
    assert hits[0].cand_index == 321
    assert hits[0].plaintext == b"hunter2"


def test_worker_unaligned_unit_no_duplicates():
    """Units not aligned to rule boundaries must neither lose nor
    duplicate hits across the boundary."""
    from dprf_tpu.engines import get_engine
    from dprf_tpu.runtime.worker import DeviceWordlistWorker
    from dprf_tpu.runtime.workunit import WorkUnit
    from dprf_tpu.engines.base import Target

    words = [b"alpha", b"beta", b"gamma", b"delta"]
    rules = [parse_rule(r) for r in (":", "u", "$1")]
    gen = WordlistRulesGenerator(words, rules, max_len=16)
    # plant: BETA (idx 1*3+1=4) and gamma1 (idx 2*3+2=8)
    targets = [Target(raw="x", digest=hashlib.md5(b"BETA").digest()),
               Target(raw="y", digest=hashlib.md5(b"gamma1").digest())]
    engine = get_engine("md5", device="jax")
    worker = DeviceWordlistWorker(engine, gen, targets, batch=6,
                                  hit_capacity=8,
                                  oracle=get_engine("md5", device="cpu"))
    # split keyspace [0,12) at 5 — mid-word, between the two plants
    hits = (worker.process(WorkUnit(0, 0, 5))
            + worker.process(WorkUnit(1, 5, 7)))
    assert sorted(h.cand_index for h in hits) == [4, 8]


def test_sharded_wordlist_step():
    import jax
    from dprf_tpu.engines import get_engine
    from dprf_tpu.ops import compare as cmp_ops
    from dprf_tpu.ops.rules_pipeline import make_sharded_wordlist_crack_step
    from dprf_tpu.parallel import make_mesh

    n_dev = len(jax.devices())
    assert n_dev >= 8
    mesh = make_mesh(8)
    B = 4                                   # words per device
    words = [f"w{i:03d}".encode() for i in range(70)]
    rules = [parse_rule(r) for r in (":", "u")]
    gen = WordlistRulesGenerator(words, rules, max_len=16)
    # plants on different chips and a later super-batch
    plant_words = {3: b"w003", 17: b"W017", 40: b"w040", 69: b"W069"}
    plant_idx = {3 * 2 + 0, 17 * 2 + 1, 40 * 2 + 0, 69 * 2 + 1}
    table = cmp_ops.make_target_table(
        [hashlib.md5(p).digest() for p in plant_words.values()])
    engine = get_engine("md5", device="jax")
    step = make_sharded_wordlist_crack_step(engine, gen, table, mesh, B,
                                            hit_capacity=4)
    super_words = step.super_words
    found = set()
    for w0 in range(0, len(words), super_words):
        nw = min(super_words, len(words) - w0)
        total, counts, lanes, tpos = step(jnp.int32(w0), jnp.int32(nw))
        for lane in np.asarray(lanes).ravel():
            if lane < 0:
                continue
            # lanes are window-relative keyspace offsets (one runtime
            # convention; parallel/sharded.py)
            found.add(w0 * 2 + int(lane))
    assert found == plant_idx


def test_builtin_rulesets_device_equivalence():
    """Every rule line of every builtin set (incl. the published best64
    reconstruction) produces identical words/rejections through the
    device compiler and the CPU oracle interpreter."""
    from dprf_tpu.rules.parser import BUILTIN_RULESETS, load_rules

    words = [b"password", b"Summer", b"a", b"", b"Pa55 word!", b"qwertyuiop"]
    ML = 20
    B = len(words)
    buf = np.zeros((B, ML), dtype=np.uint8)
    lens = np.zeros((B,), dtype=np.int32)
    for i, w in enumerate(words):
        buf[i, :len(w)] = np.frombuffer(w, dtype=np.uint8)
        lens[i] = len(w)
    w_dev, l_dev = jnp.asarray(buf), jnp.asarray(lens)
    v_dev = jnp.ones((B,), dtype=bool)

    for name in BUILTIN_RULESETS:
        for ops in load_rules(name):
            out_w, out_l, out_v = map(np.asarray,
                                      apply_rule_dev(w_dev, l_dev, v_dev,
                                                     ops, ML))
            for i, word in enumerate(words):
                want = apply_rule_cpu(word, ops, max_len=ML)
                if want is None:
                    assert not out_v[i], (name, ops, word)
                else:
                    assert out_v[i], (name, ops, word)
                    assert bytes(out_w[i, :out_l[i]]) == want, \
                        (name, ops, word)
