"""phpass (WordPress/phpBB portable hashes): itoa64 codec round-trips,
a published vector, device-vs-oracle digests, worker end-to-end, and
the CLI surface.  Costs are kept at 2^7 (the format's minimum) so the
serial chains stay test-sized; the chain structure is identical at the
production 2^13."""

import hashlib

import numpy as np
import jax.numpy as jnp
import pytest

# device-pipeline compiles: full suite / tier-1, excluded from the <5-min
# smoke tier (tools/check_markers.py enforces an explicit tier decision)
pytestmark = pytest.mark.compileheavy

from dprf_tpu.engines import get_engine
from dprf_tpu.engines.cpu.phpass import (decode64, encode64, parse_phpass,
                                         phpass_hash, phpass_raw)
from dprf_tpu.generators.mask import MaskGenerator
from dprf_tpu.runtime.workunit import WorkUnit


def test_encode64_roundtrip():
    for data in (b"\x00" * 16, bytes(range(16)), b"\xff" * 16,
                 hashlib.md5(b"x").digest()):
        assert decode64(encode64(data), 16) == data


def test_published_vector():
    """The reference phpass test vector (Openwall's phpass 0.3 README):
    'test12345' with $P$9IQRaTwm... verifies."""
    line = "$P$9IQRaTwmfeRo7ud9Fh4E2PdI0S3r.L0"
    count, salt, digest = parse_phpass(line)
    assert count == 1 << 11
    assert phpass_raw(b"test12345", salt, count) == digest


def test_hash_roundtrip_and_parse():
    line = phpass_hash(b"hunter2", b"saltsalt", 7)
    count, salt, digest = parse_phpass(line)
    assert count == 128 and salt == b"saltsalt"
    assert phpass_raw(b"hunter2", salt, count) == digest


def test_device_digest_matches_oracle():
    import random
    from dprf_tpu.engines.device.phpass import phpass_digest_batch

    rng = random.Random(400)
    cands = [bytes(rng.randrange(1, 256)
                   for _ in range(rng.randrange(0, 24)))
             for _ in range(16)]
    salt = b"NaClNaCl"
    count = 128
    maxlen = max(len(c) for c in cands)
    buf = np.zeros((len(cands), maxlen), np.uint8)
    lens = np.zeros((len(cands),), np.int32)
    for i, c in enumerate(cands):
        buf[i, :len(c)] = np.frombuffer(c, np.uint8)
        lens[i] = len(c)
    dw = phpass_digest_batch(jnp.asarray(buf), jnp.asarray(lens),
                             jnp.asarray(np.frombuffer(salt, np.uint8)),
                             jnp.int32(count))
    got = [np.asarray(dw)[i].astype("<u4").tobytes() for i in
           range(len(cands))]
    want = [phpass_raw(c, salt, count) for c in cands]
    assert got == want


def test_mask_worker_end_to_end():
    dev = get_engine("phpass", "jax")
    cpu = get_engine("phpass", "cpu")
    gen = MaskGenerator("?l?d?l")
    secret = b"k9q"
    t = dev.parse_target(phpass_hash(secret, b"abcdefgh", 7))
    w = dev.make_mask_worker(gen, [t], batch=1024, hit_capacity=8,
                             oracle=cpu)
    hits = w.process(WorkUnit(0, 0, gen.keyspace))
    assert [(h.target_index, h.plaintext) for h in hits] == [(0, secret)]


def test_wordlist_worker_with_rules():
    from dprf_tpu.generators.wordlist import WordlistRulesGenerator
    from dprf_tpu.rules.parser import parse_rule

    dev = get_engine("phpass", "jax")
    cpu = get_engine("phpass", "cpu")
    words = [b"winter", b"spring", b"summer"]
    rules = [parse_rule(":"), parse_rule("c"), parse_rule("$1")]
    gen = WordlistRulesGenerator(words, rules, max_len=20)
    secret = b"Spring"
    t = dev.parse_target(phpass_hash(secret, b"12345678", 7, tag="$H$"))
    w = dev.make_wordlist_worker(gen, [t], batch=64, hit_capacity=8,
                                 oracle=cpu)
    hits = w.process(WorkUnit(0, 0, gen.keyspace))
    assert [(h.target_index, h.plaintext) for h in hits] == [(0, secret)]


def test_sharded_phpass_worker():
    import jax
    from dprf_tpu.parallel.mesh import make_mesh

    assert len(jax.devices()) >= 8
    dev = get_engine("phpass", "jax")
    cpu = get_engine("phpass", "cpu")
    gen = MaskGenerator("?d?d?l")
    secret = b"77z"
    t = dev.parse_target(phpass_hash(secret, b"qrstuvwx", 7))
    w = dev.make_sharded_mask_worker(gen, [t], make_mesh(8),
                                     batch_per_device=64,
                                     hit_capacity=8, oracle=cpu)
    hits = w.process(WorkUnit(0, 0, gen.keyspace))
    assert [(h.target_index, h.plaintext) for h in hits] == [(0, secret)]


def test_cli_phpass_crack(tmp_path, capsys):
    from dprf_tpu.cli import main

    line = phpass_hash(b"za9", b"ABCDEFGH", 7)
    hf = tmp_path / "h.txt"
    hf.write_text(line + "\n")
    rc = main(["crack", "?l?l?d", str(hf), "--engine", "phpass",
               "--device", "tpu", "--no-potfile", "--batch", "2048",
               "-q"])
    out = capsys.readouterr().out
    assert rc == 0 and f"{line}:za9" in out


def test_parse_rejects_garbage():
    cpu = get_engine("phpass", "cpu")
    for bad in ("$P$", "$X$9IQRaTwmfeRo7ud9Fh4E2PdI0S3r.L0",
                "$P$!IQRaTwmfeRo7ud9Fh4E2PdI0S3r.L0"):
        with pytest.raises(ValueError):
            cpu.parse_target(bad)
