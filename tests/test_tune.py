"""Adaptive tuning subsystem (ISSUE 2): batch autotuner sweep logic,
persistent cache + environment invalidation, throughput-adaptive unit
sizing (the simulated-clock convergence acceptance case), RPC wiring,
session persistence, and the CLI/bench warm-start paths."""

import hashlib
import json
import os

import pytest

pytestmark = pytest.mark.smoke

from dprf_tpu import tune
from dprf_tpu.runtime.dispatcher import Dispatcher
from dprf_tpu.runtime.session import SessionJournal
from dprf_tpu.runtime.workunit import WorkUnit
from dprf_tpu.telemetry import MetricsRegistry
from dprf_tpu.tune import (AdaptiveUnitSizer, TuningCache,
                           geometric_ladder, sweep)


class FakeClock:
    def __init__(self):
        self.t = 0.0

    def __call__(self):
        return self.t


class FakeWorker:
    """Deterministic worker: fixed compile cost on the first unit,
    then a constant simulated throughput."""

    def __init__(self, clk, rate, compile_s, stride):
        self.stride = stride
        self._clk = clk
        self._rate = rate
        self._compile = compile_s
        self._first = True

    def process(self, unit):
        if self._first:
            self._clk.t += self._compile
            self._first = False
        self._clk.t += unit.length / self._rate
        return []


# ---------------------------------------------------------------------------
# autotuner sweep

def _rates(table):
    return lambda batch: table[batch]


def test_sweep_picks_fastest_batch_and_stops_on_saturation():
    clk = FakeClock()
    rate = _rates({256: 1e3, 1024: 4e3, 4096: 8e3, 16384: 7e3,
                   65536: 6e3})

    def make_worker(batch):
        return FakeWorker(clk, rate(batch), compile_s=0.1, stride=batch)

    res = sweep(make_worker, keyspace=1 << 40,
                ladder=[256, 1024, 4096, 16384, 65536],
                probe_seconds=1.0, clock=clk)
    assert res.batch == 4096
    assert res.source == "swept" and res.tuned
    # patience=2: both post-peak rungs measured, then the ladder stops
    assert [p.batch for p in res.swept] == [256, 1024, 4096, 16384,
                                            65536]
    assert res.rate_hs == pytest.approx(8e3, rel=0.01)


def test_sweep_hbm_headroom_guard_stops_the_ladder(monkeypatch):
    """ISSUE 13: a projected next-rung footprint past the device's
    free bytes stops the climb before the allocation failure; a
    backend without memory stats (free None) never stops it."""
    from dprf_tpu.tune import autotuner
    clk = FakeClock()

    class FakeEngine:
        name = "md5"

    def make_worker(batch):
        w = FakeWorker(clk, 1e3, compile_s=0.1, stride=batch)
        w.engine = FakeEngine()
        return w

    # analyzed footprint: 1 KiB/candidate at the current rung; free
    # HBM fits 2048 candidates -- the 4096 rung must not build
    class FakeProgs:
        def peak_bytes_for(self, engine, batch):
            assert engine == "md5"
            return batch * 1024         # this rung's own footprint

        def analyze_pending(self):
            return 0

    monkeypatch.setattr(autotuner, "_over_hbm_headroom",
                        autotuner._over_hbm_headroom)
    from dprf_tpu.telemetry import devstats, programs
    monkeypatch.setattr(devstats, "bytes_free",
                        lambda snap=None: 2048 * 1024)
    monkeypatch.setattr(programs, "get_programs",
                        lambda programs=None: FakeProgs())
    res = sweep(make_worker, keyspace=1 << 40,
                ladder=[1024, 4096, 16384], probe_seconds=1.0,
                clock=clk)
    assert [p.batch for p in res.swept] == [1024]
    # no memory stats -> the ladder runs to saturation/patience
    monkeypatch.setattr(devstats, "bytes_free", lambda snap=None: None)
    clk2 = FakeClock()

    def make_worker2(batch):
        return FakeWorker(clk2, 1e3, compile_s=0.1, stride=batch)

    res2 = sweep(make_worker2, keyspace=1 << 40,
                 ladder=[1024, 4096, 16384], probe_seconds=1.0,
                 clock=clk2)
    assert len(res2.swept) == 3


def test_tune_all_sweeps_registered_engines(monkeypatch, capsys):
    """`dprf tune --all` (ISSUE 13 satellite): every registered
    engine is attempted, failures are per-engine skips, and one JSON
    summary lands on stdout."""
    import dprf_tpu.cli as cli_mod

    swept_engines = []

    def fake_tune_one(engine_name, args, device, log):
        if engine_name == "sha256":
            raise ValueError("boom")
        swept_engines.append(engine_name)
        return {"engine": engine_name, "batch": 4096, "rate_hs": 1e6}

    monkeypatch.setattr(cli_mod, "_tune_one", fake_tune_one)
    monkeypatch.setattr(cli_mod, "engine_names",
                        lambda dev: ["md5", "sha256", "ntlm"])
    rc = cli_mod.cmd_tune(
        cli_mod._build_parser().parse_args(["tune", "--all", "-q"]),
        __import__("dprf_tpu.utils.logging",
                   fromlist=["Log"]).Log(quiet=True))
    out = capsys.readouterr().out
    doc = json.loads(out.strip().splitlines()[-1])
    assert rc == 0
    assert doc["tuned"] == 2 and doc["skipped"] == 1
    assert doc["skips"][0]["engine"] == "sha256"
    assert sorted(swept_engines) == ["md5", "ntlm"]


def test_tune_requires_engine_or_all():
    import dprf_tpu.cli as cli_mod
    from dprf_tpu.utils.logging import Log
    args = cli_mod._build_parser().parse_args(["tune", "-q"])
    assert cli_mod.cmd_tune(args, Log(quiet=True)) == 2


def test_sweep_compile_budget_stops_the_ladder():
    clk = FakeClock()
    rate = _rates({256: 1e3, 1024: 2e3, 4096: 4e3, 16384: 8e3})

    def make_worker(batch):
        return FakeWorker(clk, rate(batch), compile_s=0.001 * batch,
                          stride=batch)

    res = sweep(make_worker, keyspace=1 << 40,
                ladder=[256, 1024, 4096, 16384],
                probe_seconds=1.0, compile_budget_s=10.0, clock=clk)
    # 16384 compiles for 16s > budget: recorded, never considered
    assert res.batch == 4096
    assert res.swept[-1].batch == 16384
    assert res.swept[-1].error == "over compile budget"


def test_sweep_build_failure_stops_the_ladder():
    clk = FakeClock()

    def make_worker(batch):
        if batch >= 4096:
            raise MemoryError("RESOURCE_EXHAUSTED: HBM")
        return FakeWorker(clk, 1e3 * batch, compile_s=0.1, stride=batch)

    res = sweep(make_worker, keyspace=1 << 40,
                ladder=[256, 1024, 4096, 16384],
                probe_seconds=0.5, clock=clk)
    assert res.batch == 1024
    assert "MemoryError" in res.swept[-1].error
    assert res.swept[-1].batch == 4096      # 16384 never attempted


def test_sweep_all_rungs_failing_raises():
    def make_worker(batch):
        raise RuntimeError("no backend")

    with pytest.raises(ValueError, match="every rung"):
        sweep(make_worker, keyspace=1 << 20, ladder=[256],
              clock=FakeClock())


def test_geometric_ladder_bounds():
    assert geometric_ladder(1 << 14, 1 << 22, 4) == [
        1 << 14, 1 << 16, 1 << 18, 1 << 20, 1 << 22]
    assert geometric_ladder(100, 100) == [100]
    with pytest.raises(ValueError):
        geometric_ladder(0, 100)


# ---------------------------------------------------------------------------
# persistent cache + invalidation

def test_cache_roundtrip_and_env_invalidation(tmp_path):
    """Satellite: an entry recorded under a different jax version /
    device kind / engine rev must be IGNORED, not reused."""
    path = str(tmp_path / "tc.json")
    env = {"jax": "0.4.37", "device_kind": "cpu", "engine_rev": "abc"}
    TuningCache(path).put("k", {"batch": 1024, "rate_hs": 5e6}, env)

    c = TuningCache(path)                  # fresh load from disk
    hit = c.get("k", env)
    assert hit["batch"] == 1024 and hit["env"] == env
    for field, stale in (("jax", "9.9.9"),
                         ("device_kind", "TPU v6 lite"),
                         ("engine_rev", "defdefdefdef")):
        assert c.get("k", dict(env, **{field: stale})) is None, field
    assert c.get("other-key", env) is None


def test_cache_survives_torn_or_alien_files(tmp_path):
    path = str(tmp_path / "tc.json")
    with open(path, "w") as fh:
        fh.write('{"version": 99, "entr')      # torn foreign write
    c = TuningCache(path)
    assert c.get("k", {}) is None
    c.put("k", {"batch": 64}, {"jax": "x"})
    assert TuningCache(path).get("k", {"jax": "x"})["batch"] == 64


def test_make_key_stable_and_extra_sorted():
    a = tune.make_key("md5", attack="mask", device="jax", b=2, a=1)
    b = tune.make_key("md5", device="jax", attack="mask", a=1, b=2)
    assert a == b
    # engine-registry normalization: `dprf tune -m MD5` and a job keyed
    # on the canonical engine.name must share one entry
    assert tune.make_key("MD5", device="jax") == tune.make_key(
        "md5", device="jax")
    assert tune.make_key("md5") != tune.make_key("sha1")
    assert (tune.make_key("md5", device="jax")
            != tune.make_key("md5", device="cpu"))


def test_lookup_tuned_batch_env_validated(tmp_path, monkeypatch):
    monkeypatch.setenv("DPRF_TUNE_DIR", str(tmp_path))
    env = tune.env_fingerprint("md5", "cpu")
    key = tune.make_key("md5", attack="mask", device="cpu")
    tune.default_cache().put(key, {"batch": 2048}, env)
    assert tune.lookup_tuned_batch("md5", attack="mask",
                                   device="cpu") == 2048
    # same key re-recorded under a stale jax version: read as a miss
    tune.default_cache().put(key, {"batch": 4096},
                             dict(env, jax="0.0.0"))
    assert tune.lookup_tuned_batch("md5", attack="mask",
                                   device="cpu") is None


def test_key_extras_fork_the_optimum(tmp_path, monkeypatch):
    """Satellite (ISSUE 3): hit_capacity and rules-set cardinality are
    key dimensions -- an entry tuned under one must never alias a
    lookup under another."""
    monkeypatch.setenv("DPRF_TUNE_DIR", str(tmp_path))
    env = tune.env_fingerprint("md5", "cpu")
    tune.default_cache().put(
        tune.make_key("md5", attack="mask", device="cpu", hit_cap=64),
        {"batch": 2048}, env)
    assert tune.lookup_tuned_batch(
        "md5", attack="mask", device="cpu",
        extras={"hit_cap": 64}) == 2048
    # a raised --hit-cap is a DIFFERENT optimum: must read as a miss
    assert tune.lookup_tuned_batch(
        "md5", attack="mask", device="cpu",
        extras={"hit_cap": 1024}) is None
    # wordlist entries fork on the rules-set cardinality
    tune.default_cache().put(
        tune.make_key("sha256", attack="wordlist", device="cpu",
                      hit_cap=64, rules_n=64),
        {"batch": 8192}, env)
    assert tune.lookup_tuned_batch(
        "sha256", attack="wordlist", device="cpu",
        extras={"hit_cap": 64, "rules_n": 64}) == 8192
    assert tune.lookup_tuned_batch(
        "sha256", attack="wordlist", device="cpu",
        extras={"hit_cap": 64, "rules_n": 77}) is None
    # record_tuned_batch round-trips the same extras
    from dprf_tpu.tune import TuneResult, record_tuned_batch
    res = TuneResult(4096, 1e6, 0.5, [])
    record_tuned_batch("md5", "mask", "cpu", res,
                       extras={"hit_cap": 128})
    assert tune.lookup_tuned_batch(
        "md5", attack="mask", device="cpu",
        extras={"hit_cap": 128}) == 4096


def test_engine_rev_tracks_source_identity():
    assert tune.engine_rev("md5", "cpu") == tune.engine_rev("md5", "cpu")
    assert tune.engine_rev("md5", "cpu") != "unknown"
    assert tune.engine_rev("no-such-engine", "cpu") == "unknown"


# ---------------------------------------------------------------------------
# adaptive unit sizing

def test_unit_sizes_converge_to_target_under_10x_worker_spread():
    """Acceptance: simulated-clock dispatcher run with a 10x speed
    spread -- each worker's units converge to the target
    seconds-per-unit (so the fast worker gets 10x longer units)."""
    m = MetricsRegistry()
    clk = FakeClock()
    target = 5.0
    sizer = AdaptiveUnitSizer(initial=10_000, target_seconds=target,
                              min_unit=1, max_unit=1 << 30, registry=m)
    d = Dispatcher(keyspace=10**9, unit_size=10_000, lease_timeout=1e12,
                   clock=clk, sizer=sizer, registry=m)
    rates = {"fast": 1e6, "slow": 1e5}
    last = {}
    for _ in range(20):
        for wid, rate in rates.items():
            u = d.lease(wid)
            elapsed = u.length / rate            # simulated wall time
            clk.t += elapsed
            d.complete(u.unit_id, elapsed=elapsed)
            last[wid] = u.length
    for wid, rate in rates.items():
        seconds_per_unit = last[wid] / rate
        assert seconds_per_unit == pytest.approx(target, rel=0.15), wid
    ratio = last["fast"] / last["slow"]
    assert 8.0 < ratio < 12.0
    assert m.gauge("dprf_unit_target_seconds").value() == target
    assert m.gauge("dprf_unit_size").value() > 0


def test_unit_sizer_clamps_aligns_and_ignores_junk():
    sizer = AdaptiveUnitSizer(initial=1000, target_seconds=10.0,
                              min_unit=64, max_unit=4096, align=64,
                              registry=MetricsRegistry())
    assert sizer.next_size("w") == 1000 - (1000 % 64)   # no history
    sizer.observe("w", 0, 1.0)                          # junk: dropped
    sizer.observe("w", 100, 0.0)
    sizer.observe("w", 100, -3.0)
    assert sizer.rate("w") is None
    sizer.observe("w", 1_000_000, 1.0)                  # very fast
    assert sizer.next_size("w") == 4096                 # max clamp
    sizer2 = AdaptiveUnitSizer(initial=1000, target_seconds=10.0,
                               min_unit=512, max_unit=4096,
                               registry=MetricsRegistry())
    sizer2.observe("w", 10, 100.0)                      # very slow
    assert sizer2.next_size("w") == 512                 # min clamp


def test_dispatcher_reissued_units_keep_their_geometry():
    """Adaptive sizing applies to lazily-generated units only: a
    reissued unit must come back with its original range."""
    m = MetricsRegistry()
    sizer = AdaptiveUnitSizer(initial=100, target_seconds=10.0,
                              min_unit=1, registry=m)
    d = Dispatcher(keyspace=100_000, unit_size=100, sizer=sizer,
                   registry=m)
    u = d.lease("w0")
    assert u.length == 100
    d.complete(u.unit_id, elapsed=1.0)     # 100/s -> next target 1000
    u2 = d.lease("w0")
    assert u2.length == 1000
    d.fail(u2.unit_id)
    u3 = d.lease("w0")                     # reissue: same geometry
    assert (u3.start, u3.end) == (u2.start, u2.end)


def test_rpc_complete_elapsed_feeds_the_sizer():
    """The existing RPC complete path carries the throughput report;
    junk elapsed values must be ignored."""
    from dprf_tpu.runtime.rpc import CoordinatorState

    m = MetricsRegistry()
    sizer = AdaptiveUnitSizer(initial=100, target_seconds=10.0,
                              min_unit=1, registry=m)
    d = Dispatcher(keyspace=1_000_000, unit_size=100, sizer=sizer,
                   registry=m)
    state = CoordinatorState({"engine": "md5"}, d, n_targets=1,
                             registry=m)
    resp = state.op_lease({"worker_id": "w0"})
    assert resp["unit"]["length"] == 100
    state.op_complete({"unit_id": resp["unit"]["id"], "hits": [],
                       "worker_id": "w0", "elapsed": 2.0})  # 50/s
    resp = state.op_lease({"worker_id": "w0"})
    assert resp["unit"]["length"] == 500
    # junk elapsed: no crash, no observation folded in
    state.op_complete({"unit_id": resp["unit"]["id"], "hits": [],
                       "worker_id": "w0", "elapsed": "soon"})
    assert sizer.rate("w0") == pytest.approx(50.0)
    st = state.op_status({})
    assert st["parked"] == 0 and st["parked_indices"] == 0


# ---------------------------------------------------------------------------
# session persistence

def test_session_journal_tune_records_roundtrip(tmp_path):
    p = str(tmp_path / "job.session")
    j = SessionJournal(p)
    key = tune.make_key("md5", attack="mask", device="jax")
    j.record_tuning(key, {"batch": 4096})   # pre-open: buffered
    j.open({"engine": "md5", "fingerprint": "f"})
    j.record_tuning("k2", {"batch": 512})
    j.close()
    st = SessionJournal.load(p)
    assert st.tuning[key]["batch"] == 4096
    assert st.tuning["k2"]["batch"] == 512
    assert st.spec["fingerprint"] == "f"    # header still first


# ---------------------------------------------------------------------------
# CLI + bench end to end (CPU oracle path: fast, no compiles)

def test_cli_tune_writes_cache_then_bench_and_crack_warm_start(
        tmp_path, monkeypatch, capsys):
    """Acceptance: `dprf tune` writes the cache; a later bench and a
    `--batch auto` job both LOAD it -- no re-sweep -- observable via
    `tuned: true` in the bench JSON and the dprf_tuned_batch gauge."""
    from dprf_tpu.bench import run_bench
    from dprf_tpu.cli import main as cli_main

    monkeypatch.setenv("DPRF_TUNE_DIR", str(tmp_path))
    rc = cli_main(["tune", "--engine", "md5", "--device", "cpu",
                   "--mask", "?l?l?l", "--seconds", "0.05",
                   "--min-batch", "256", "--max-batch", "1024",
                   "--ladder-factor", "2", "-q"])
    assert rc == 0
    doc = json.loads(capsys.readouterr().out.strip().splitlines()[-1])
    assert doc["batch"] in (256, 512, 1024)
    assert [p["batch"] for p in doc["swept"]]       # the sweep ran
    cache_file = tmp_path / "tune_cache.json"
    assert cache_file.exists()

    # bench consumes the cache: tuned flag flips true, batch matches
    res = run_bench(engine="md5", device="cpu", mask="?l?l?l?l",
                    batch="auto", seconds=0.05)
    assert res["tuned"] is True

    # a --batch auto job loads the same entry (no sweep in the job
    # path at all; the gauge records what it ran with)
    hashfile = tmp_path / "hashes.txt"
    hashfile.write_text(hashlib.md5(b"abc").hexdigest() + "\n")
    rc = cli_main(["crack", "?l?l?l", str(hashfile), "--engine", "md5",
                   "--device", "cpu", "--no-potfile",
                   "--unit-seconds", "0", "-q"])
    assert rc == 0
    from dprf_tpu.telemetry import DEFAULT
    g = DEFAULT.get("dprf_tuned_batch")
    assert g is not None
    assert g.value(engine="md5", device="cpu",
                   attack="mask") == doc["batch"]


def test_bench_auto_without_cache_reports_untuned(tmp_path, monkeypatch):
    from dprf_tpu.bench import run_bench

    monkeypatch.setenv("DPRF_TUNE_DIR", str(tmp_path / "empty"))
    res = run_bench(engine="md5", device="cpu", mask="?l?l?l?l",
                    batch="auto", seconds=0.05)
    assert res["tuned"] is False
    assert res["value"] > 0


def test_cli_batch_auto_resumes_from_session_journal(tmp_path,
                                                     monkeypatch):
    """A resumed session reuses its journaled tuning decision even
    when the persistent cache is gone (different machine)."""
    from dprf_tpu.cli import main as cli_main

    monkeypatch.setenv("DPRF_TUNE_DIR", str(tmp_path / "cachedir"))
    env = tune.env_fingerprint("md5", "cpu")
    # the job-side key carries the hit_cap extra (ISSUE 3 satellite);
    # the CLI's default --hit-cap is 64
    key = tune.make_key("md5", attack="mask", device="cpu", hit_cap=64)
    tune.default_cache().put(key, {"batch": 512}, env)

    hashfile = tmp_path / "hashes.txt"
    hashfile.write_text(hashlib.md5(b"zz").hexdigest() + "\n")
    session = str(tmp_path / "job.session")
    rc = cli_main(["crack", "?l?l", str(hashfile), "--engine", "md5",
                   "--device", "cpu", "--no-potfile",
                   "--session", session, "--unit-seconds", "0", "-q"])
    assert rc == 0
    st = SessionJournal.load(session)
    assert st.tuning[key]["batch"] == 512   # decision journaled

    # cache vanishes (new machine); the journal alone drives resume
    monkeypatch.setenv("DPRF_TUNE_DIR", str(tmp_path / "elsewhere"))
    rc = cli_main(["crack", "?l?l", str(hashfile), "--engine", "md5",
                   "--device", "cpu", "--no-potfile",
                   "--session", session, "--restore",
                   "--unit-seconds", "0", "-q"])
    assert rc == 0
    from dprf_tpu.telemetry import DEFAULT
    assert DEFAULT.get("dprf_tuned_batch").value(
        engine="md5", device="cpu", attack="mask") == 512


# ---------------------------------------------------------------------------
# marker-hygiene tool (satellite: runs at the top of tier-1)

def test_check_markers_tool_passes_on_this_suite_and_fails_on_unmarked(
        tmp_path):
    import subprocess
    import sys

    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    tool = os.path.join(repo, "tools", "check_markers.py")
    proc = subprocess.run([sys.executable, tool], capture_output=True,
                          text=True)
    assert proc.returncode == 0, proc.stdout + proc.stderr

    bad = tmp_path / "test_unmarked_device.py"
    bad.write_text(
        "def test_x():\n"
        "    from dprf_tpu.ops.pallas_mask import TILE\n"
        "    assert TILE\n")
    proc = subprocess.run([sys.executable, tool, str(tmp_path)],
                          capture_output=True, text=True)
    assert proc.returncode == 1
    assert "test_unmarked_device.py" in proc.stdout
    # a tier marker satisfies the rule
    bad.write_text(
        "import pytest\n"
        "pytestmark = pytest.mark.compileheavy\n"
        "def test_x():\n"
        "    from dprf_tpu.ops.pallas_mask import TILE\n"
        "    assert TILE\n")
    proc = subprocess.run([sys.executable, tool, str(tmp_path)],
                          capture_output=True, text=True)
    assert proc.returncode == 0, proc.stdout + proc.stderr
