"""Extended Pallas kernels (ops/pallas_ext.py) vs the CPU oracles:
salted $pass.$salt / $salt.$pass, nested double-hash, and mysql41.

Interpret mode on the CPU backend covers the md5/sha1 chains; the
sha256-stage variants use the eager body emulator (the statically
unrolled sha256 rounds don't compile on XLA:CPU in reasonable time --
same split as test_pallas_mask).
"""

import numpy as np
import jax.numpy as jnp
import pytest

# device-pipeline compiles: full suite / tier-1, excluded from the <5-min
# smoke tier (tools/check_markers.py enforces an explicit tier decision)
pytestmark = pytest.mark.compileheavy

from dprf_tpu.engines import get_engine
from dprf_tpu.generators.mask import MaskGenerator
from dprf_tpu.ops import pallas_ext as pe
from dprf_tpu.runtime.workunit import WorkUnit

BATCH = pe.SUB * 128


def _tw(engine_name: str, plain: bytes, salt=None) -> np.ndarray:
    """Final digest words in the engine's layout via the CPU oracle."""
    eng = get_engine(engine_name, device="cpu")
    params = {"salt": salt} if salt is not None else None
    d = eng.hash_batch([plain], params=params)[0]
    dt = "<u4" if _little(engine_name) else ">u4"
    return np.frombuffer(d, dtype=dt).astype(np.uint32)


def _little(engine_name: str) -> bool:
    if engine_name == "mysql41":
        return False
    if engine_name in pe.NESTED_COMBOS:
        outer = pe.NESTED_COMBOS[engine_name][0]
        return outer == "md5"
    return engine_name.startswith("md5")


def _run_fn(fn, gen, *extra, n_valid=None):
    base = jnp.asarray(gen.digits(0), jnp.int32)
    c, l = fn(base, jnp.asarray([n_valid], jnp.int32), *extra)
    c, l = np.asarray(c)[:, 0], np.asarray(l)[:, 0]
    return [int(t * pe.SUB * 128 + l[t]) for t in np.nonzero(c)[0]], \
        int(c.sum())


@pytest.mark.parametrize("name", ["md5(md5)", "sha1(sha1)", "md5(sha1)",
                                  "sha1(md5)", "mysql41"])
def test_nested_kernel_interpret_finds_plant(name):
    gen = MaskGenerator("?l?l?l?l")
    plant = 2 * pe.SUB * 128 + 77     # tile 2, lane 77
    tw = _tw(name, gen.candidate(plant))
    fn = pe.make_ext_pallas_fn(name, gen, tw, BATCH * 4, interpret=True)
    hits, total = _run_fn(fn, gen, n_valid=BATCH * 4)
    assert hits == [plant] and total == 1


@pytest.mark.parametrize("name", ["sha256(md5)", "sha256(sha1)"])
def test_sha256_nested_emulated(name):
    gen = MaskGenerator("?l?l?l")
    plant = 321
    tw = _tw(name, gen.candidate(plant))
    counts, lanes = pe.emulate_ext_kernel(name, gen, tw, BATCH,
                                          gen.digits(0), BATCH)
    c, l = counts[:, 0], lanes[:, 0]
    hits = [int(t * pe.SUB * 128 + l[t]) for t in np.nonzero(c)[0]]
    assert hits == [plant]


def test_nested_multi_target_bloom():
    gen = MaskGenerator("?l?l?l?l")
    plants = [5, pe.SUB * 128 + 9, 3 * pe.SUB * 128 + 100]
    tws = np.stack([_tw("md5(md5)", gen.candidate(i)) for i in plants])
    rng = np.random.RandomState(7)
    noise = rng.randint(0, 2**32, (47, 4), dtype=np.uint32)
    all_t = np.concatenate([noise[:20], tws, noise[20:]])
    fn = pe.make_ext_pallas_fn("md5(md5)", gen, all_t, BATCH * 4,
                               interpret=True)
    hits, total = _run_fn(fn, gen, n_valid=BATCH * 4)
    # Bloom maybes: every plant must surface; false maybes tolerated
    assert set(plants) <= set(hits)
    assert total <= len(plants) + 2


@pytest.mark.parametrize("algo,order", [("md5", "ps"), ("md5", "sp"),
                                        ("sha1", "ps"), ("sha1", "sp")])
@pytest.mark.parametrize("salt", [b"ab", b"s3cr3t!", b"0123456789abcdef"])
def test_salted_kernel_interpret(algo, order, salt):
    gen = MaskGenerator("?l?l?l?l")
    plant = pe.SUB * 128 + 31
    tw = _tw(f"{algo}-{order}", gen.candidate(plant), salt=salt)
    fn = pe.make_salted_pallas_fn(algo, order, gen, BATCH * 2,
                                  len(salt), interpret=True)
    salt_dev = jnp.asarray(np.frombuffer(salt, np.uint8).astype(np.int32))
    tgt_dev = jnp.asarray(tw.view(np.int32))
    hits, total = _run_fn(fn, gen, salt_dev, tgt_dev,
                          n_valid=BATCH * 2)
    assert hits == [plant] and total == 1


@pytest.mark.parametrize("order", ["ps", "sp"])
def test_salted_sha256_emulated(order):
    gen = MaskGenerator("?l?l?l")
    salt = b"NaCl"
    plant = 1234
    tw = _tw(f"sha256-{order}", gen.candidate(plant), salt=salt)
    counts, lanes = pe.emulate_ext_kernel(
        "sha256", gen, tw, BATCH, gen.digits(0), BATCH,
        order=order, salt=salt)
    c, l = counts[:, 0], lanes[:, 0]
    hits = [int(t * pe.SUB * 128 + l[t]) for t in np.nonzero(c)[0]]
    assert hits == [plant]


def test_salted_worker_selected_and_cracks(monkeypatch):
    """DPRF_PALLAS=1 routes eligible salted mask jobs to the kernel
    worker; mixed salt lengths compile one kernel per length and every
    target cracks with its original index."""
    from dprf_tpu.engines.device.salted import PallasSaltedMaskWorker

    monkeypatch.setenv("DPRF_PALLAS", "1")
    gen = MaskGenerator("?l?l?l?l")
    cpu = get_engine("md5-ps", device="cpu")
    dev = get_engine("md5-ps", device="jax")
    plants = [(123, b"aa"), (45000, b"longersalt!")]
    targets = []
    for idx, salt in plants:
        d = cpu.hash_batch([gen.candidate(idx)],
                           params={"salt": salt})[0]
        targets.append(cpu.parse_target(d.hex() + ":" + salt.decode()))
    w = dev.make_mask_worker(gen, targets, batch=1 << 15,
                             hit_capacity=8, oracle=cpu)
    assert isinstance(w, PallasSaltedMaskWorker)
    assert len(w._ksteps) == 2      # one compiled kernel per salt len
    hits = w.process(WorkUnit(0, 0, gen.keyspace))
    assert {(h.target_index, h.cand_index) for h in hits} == \
        {(0, 123), (1, 45000)}


def test_salted_worker_falls_back_when_ineligible(monkeypatch):
    """sha512 has no 32-bit kernel core -> XLA salted worker."""
    from dprf_tpu.engines.device.salted import (PallasSaltedMaskWorker,
                                                SaltedMaskWorker)

    monkeypatch.setenv("DPRF_PALLAS", "1")
    gen = MaskGenerator("?l?l?l")
    cpu = get_engine("sha512-ps", device="cpu")
    dev = get_engine("sha512-ps", device="jax")
    d = cpu.hash_batch([b"abc"], params={"salt": b"xy"})[0]
    t = cpu.parse_target(d.hex() + ":xy")
    w = dev.make_mask_worker(gen, [t], batch=4096, hit_capacity=8,
                             oracle=cpu)
    assert isinstance(w, SaltedMaskWorker)
    assert not isinstance(w, PallasSaltedMaskWorker)


def test_nested_engine_uses_kernel_worker(monkeypatch):
    """Nested names flow through the standard PallasMaskWorker via the
    pallas_mask dispatch (single target, exact compare)."""
    from dprf_tpu.runtime.worker import PallasMaskWorker

    monkeypatch.setenv("DPRF_PALLAS", "1")
    gen = MaskGenerator("?l?l?l?l")
    cpu = get_engine("md5(md5)", device="cpu")
    dev = get_engine("md5(md5)", device="jax")
    plant = 31337
    d = cpu.hash_batch([gen.candidate(plant)])[0]
    t = cpu.parse_target(d.hex())
    w = dev.make_mask_worker(gen, [t], batch=1 << 15, hit_capacity=8,
                             oracle=cpu)
    assert isinstance(w, PallasMaskWorker)
    hits = w.process(WorkUnit(0, 0, gen.keyspace))
    assert [(h.target_index, h.cand_index) for h in hits] == [(0, plant)]


def test_eligibility_rules():
    gen = MaskGenerator("?l?l?l?l")
    # nested: known combos only; candidate must fit one block
    assert pe.nested_eligible("md5(md5)", gen, 1)
    assert pe.nested_eligible("mysql41", gen, 50)
    assert not pe.nested_eligible("md5(sha256)", gen, 1)   # no such combo
    assert not pe.nested_eligible("md5(md5)", gen, 0)
    long = MaskGenerator("?l" * 56)
    assert not pe.nested_eligible("md5(md5)", long, 1)
    # salted: algo must have a core; salt must fit the block
    assert pe.salted_eligible("md5", "ps", gen, [4, 12])
    assert not pe.salted_eligible("sha512", "ps", gen, [4])
    assert not pe.salted_eligible("md5", "xx", gen, [4])
    assert not pe.salted_eligible("md5", "ps", gen, [52])  # 4+52 > 55
    assert not pe.salted_eligible("md5", "ps", gen, [])
    assert not pe.salted_eligible("md5", "ps", gen,
                                  list(range(1, 10)))     # 9 lengths


def test_nested_and_salted_kernels_markov_mask():
    """Markov-permuted charsets ride the ext kernels through the same
    lane-axis LUT input as pallas_mask (r5): planted hits at exact
    indices for a nested and a salted variant, interpret mode."""
    counts = np.zeros((4, 256), np.uint64)
    rng = np.random.RandomState(5)
    counts[:, :] = rng.randint(1, 10**6, (4, 256))
    gen = MaskGenerator("?l?l?d", markov_counts=counts)
    from dprf_tpu.ops.pallas_mask import position_tables
    assert position_tables(gen.charsets)[1] is not None   # LUT in play

    plant = pe.SUB * 128 + 9          # tile 1, lane 9
    tw = _tw("md5(md5)", gen.candidate(plant))
    fn = pe.make_ext_pallas_fn("md5(md5)", gen, tw, BATCH * 2,
                               interpret=True)
    hits, total = _run_fn(fn, gen, n_valid=BATCH * 2)
    assert hits == [plant] and total == 1

    import hashlib as _hl
    salt = b"na"
    plain = gen.candidate(plant)
    tw2 = np.frombuffer(_hl.md5(plain + salt).digest(),
                        "<u4").astype(np.uint32)
    fn2 = pe.make_salted_pallas_fn("md5", "ps", gen, BATCH * 2,
                                   len(salt), interpret=True)
    salt_dev = jnp.asarray(np.frombuffer(salt, np.uint8)
                           .astype(np.int32))
    tgt_dev = jnp.asarray(tw2.view(np.int32))
    hits2, total2 = _run_fn(fn2, gen, salt_dev, tgt_dev,
                            n_valid=BATCH * 2)
    assert hits2 == [plant] and total2 == 1
