"""sha256crypt ($5$): reference vs system crypt, device vs reference
(two-block round messages), worker end-to-end, CLI."""

import numpy as np
import jax.numpy as jnp
import pytest

# device-pipeline compiles: full suite / tier-1, excluded from the <5-min
# smoke tier (tools/check_markers.py enforces an explicit tier decision)
pytestmark = pytest.mark.compileheavy

from dprf_tpu.engines import get_engine
from dprf_tpu.engines.cpu.sha256crypt import (parse_sha256crypt,
                                              sha256crypt_hash,
                                              sha256crypt_raw)
from dprf_tpu.generators.mask import MaskGenerator
from dprf_tpu.runtime.workunit import WorkUnit


def test_against_system_crypt_if_available():
    try:
        import crypt
    except ImportError:
        pytest.skip("no crypt module")
    for pw, salt, rounds in ((b"password", b"saltstring", 5000),
                             (b"", b"zz", 5000),
                             (b"hello", b"salt", 1000)):
        spec = "$5$" + (f"rounds={rounds}$" if rounds != 5000 else "") \
            + salt.decode() + "$"
        want = crypt.crypt(pw.decode(), spec)
        if want is None:
            pytest.skip("system crypt lacks sha256crypt")
        assert sha256crypt_hash(pw, salt, rounds) == want


def test_device_digest_matches_reference():
    import random
    from dprf_tpu.engines.device.sha256crypt import \
        sha256crypt_digest_batch

    rng = random.Random(74)
    cands = [b"", b"abcdefghijklmno"] + [
        bytes(rng.randrange(1, 256) for _ in range(rng.randrange(0, 16)))
        for _ in range(5)]
    salt = b"mZ"
    maxlen = max((len(c) for c in cands), default=1) or 1
    buf = np.zeros((len(cands), maxlen), np.uint8)
    lens = np.zeros((len(cands),), np.int32)
    for i, c in enumerate(cands):
        buf[i, :len(c)] = np.frombuffer(c, np.uint8)
        lens[i] = len(c)
    sbuf = np.zeros((16,), np.uint8)
    sbuf[:len(salt)] = np.frombuffer(salt, np.uint8)
    dw = sha256crypt_digest_batch(jnp.asarray(buf), jnp.asarray(lens),
                                  jnp.asarray(sbuf),
                                  jnp.int32(len(salt)), jnp.int32(1000))
    got = [np.asarray(dw)[i].astype(">u4").tobytes()
           for i in range(len(cands))]
    assert got == [sha256crypt_raw(c, salt, 1000) for c in cands]


def test_mask_worker_end_to_end():
    dev = get_engine("sha256crypt", "jax")
    cpu = get_engine("sha256crypt", "cpu")
    gen = MaskGenerator("?l?d")
    secret = b"r3"
    t = dev.parse_target(sha256crypt_hash(secret, b"NaCl", 1000))
    w = dev.make_mask_worker(gen, [t], batch=512, hit_capacity=8,
                             oracle=cpu)
    hits = w.process(WorkUnit(0, 0, gen.keyspace))
    assert [(h.target_index, h.plaintext) for h in hits] == [(0, secret)]


def test_cli_sha256crypt_crack(tmp_path, capsys):
    from dprf_tpu.cli import main

    line = sha256crypt_hash(b"w9", b"grain", 1000)
    hf = tmp_path / "h.txt"
    hf.write_text(line + "\n")
    rc = main(["crack", "?l?d", str(hf), "--engine", "sha256crypt",
               "--device", "tpu", "--no-potfile", "--batch", "512",
               "-q"])
    out = capsys.readouterr().out
    assert rc == 0 and f"{line}:w9" in out


def test_sharded_sha256crypt_worker():
    import jax
    from dprf_tpu.parallel.mesh import make_mesh
    from dprf_tpu.runtime.workunit import WorkUnit

    assert len(jax.devices()) >= 8
    dev = get_engine("sha256crypt", "jax")
    cpu = get_engine("sha256crypt", "cpu")
    gen = MaskGenerator("?d?l")
    secret = b"3m"
    t = dev.parse_target(sha256crypt_hash(secret, b"mesa", 1000))
    w = dev.make_sharded_mask_worker(gen, [t], make_mesh(8),
                                     batch_per_device=16, hit_capacity=8,
                                     oracle=cpu)
    hits = w.process(WorkUnit(0, 0, gen.keyspace))
    assert [(h.target_index, h.plaintext) for h in hits] == [(0, secret)]
