"""Device PBKDF2-HMAC-SHA1 / WPA2-PMKID vs stdlib oracles.

Covers: RFC 6070 PBKDF2 vectors, random-candidate equivalence with
hashlib.pbkdf2_hmac, PMKID equivalence with the CPU oracle engine, and
the fused PMKID worker end-to-end (planted passphrase, multi-essid).
"""

import hashlib
import hmac as hmac_mod
import random

import jax.numpy as jnp
import numpy as np
import pytest

# device-pipeline compiles: full suite / tier-1, excluded from the <5-min
# smoke tier (tools/check_markers.py enforces an explicit tier decision)
pytestmark = pytest.mark.compileheavy

from dprf_tpu.engines import get_engine
from dprf_tpu.engines.device.pmkid import (JaxPmkidEngine,
                                           PmkidDeviceWorker)
from dprf_tpu.generators.mask import MaskGenerator
from dprf_tpu.ops import pack as pack_ops
from dprf_tpu.ops.hmac_sha1 import (hmac_key_states, hmac_sha1_20,
                                    pbkdf2_sha1_block, pbkdf2_sha1_pmk,
                                    pmkid_from_pmk)
from dprf_tpu.runtime.workunit import WorkUnit


def _pack_keys(keys: list) -> jnp.ndarray:
    maxlen = max(len(k) for k in keys)
    buf = np.zeros((len(keys), maxlen), dtype=np.uint8)
    for i, k in enumerate(keys):
        buf[i, :len(k)] = np.frombuffer(k, dtype=np.uint8)
    # zero padding beyond each key is exactly the HMAC key-block rule as
    # long as every key has the same length; tests use equal lengths.
    assert all(len(k) == maxlen for k in keys)
    return pack_ops.pack_raw(jnp.asarray(buf), maxlen, big_endian=True)


def _words_to_bytes(w: np.ndarray) -> bytes:
    return np.asarray(w).astype(">u4").tobytes()


def test_hmac_sha1_20_matches_stdlib():
    keys = [bytes([random.randrange(256) for _ in range(16)])
            for _ in range(32)]
    msg = bytes(range(20))
    kw = _pack_keys(keys)
    istate, ostate = hmac_key_states(kw)
    msg5 = jnp.broadcast_to(
        jnp.asarray(np.frombuffer(msg, dtype=">u4").astype(np.uint32)),
        (len(keys), 5))
    got = hmac_sha1_20(istate, ostate, msg5)
    for i, k in enumerate(keys):
        want = hmac_mod.new(k, msg, hashlib.sha1).digest()
        assert _words_to_bytes(got[i]) == want


@pytest.mark.parametrize("password,salt,iters,dk20", [
    # RFC 6070 test vectors (PBKDF2-HMAC-SHA1, dkLen=20)
    (b"password", b"salt", 1,
     "0c60c80f961f0e71f3a9b524af6012062fe037a6"),
    (b"password", b"salt", 2,
     "ea6c014dc72d6f8ccd1ed92ace1d41f0d8de8957"),
    (b"password", b"salt", 4096,
     "4b007901b765489abead49d926f721d065a429c1"),
])
def test_pbkdf2_rfc6070_vectors(password, salt, iters, dk20):
    kw = _pack_keys([password])
    istate, ostate = hmac_key_states(kw)
    t1 = pbkdf2_sha1_block(istate, ostate, salt, 1, iters)
    assert _words_to_bytes(t1[0]) == bytes.fromhex(dk20)


def test_pbkdf2_pmk_matches_hashlib():
    rng = random.Random(7)
    pws = [bytes(rng.randrange(0x21, 0x7F) for _ in range(10))
           for _ in range(8)]
    essid = b"TestNet-5G"
    got = pbkdf2_sha1_pmk(_pack_keys(pws), essid, iterations=128)
    for i, pw in enumerate(pws):
        want = hashlib.pbkdf2_hmac("sha1", pw, essid, 128, 32)
        assert _words_to_bytes(got[i]) == want


def test_full_4096_iteration_pmk():
    pw = b"password"
    essid = b"linksys"
    got = pbkdf2_sha1_pmk(_pack_keys([pw]), essid, iterations=4096)
    want = hashlib.pbkdf2_hmac("sha1", pw, essid, 4096, 32)
    assert _words_to_bytes(got[0]) == want


def test_pmkid_matches_cpu_oracle():
    oracle = get_engine("wpa2-pmkid", device="cpu")
    pw = b"hunter2hunter2"
    essid, ap, sta = b"CoffeeShop", bytes(range(6)), bytes(range(6, 12))
    pmk = hashlib.pbkdf2_hmac("sha1", pw, essid, 4096, 32)
    pmk_words = jnp.asarray(
        np.frombuffer(pmk, dtype=">u4").astype(np.uint32))[None, :]
    got = pmkid_from_pmk(pmk_words, ap, sta)
    want = oracle.hash_batch(
        [pw], params={"essid": essid, "mac_ap": ap, "mac_sta": sta})[0]
    assert _words_to_bytes(got[0]) == want


def _target_line(pw: bytes, essid: bytes, ap: bytes, sta: bytes) -> str:
    pmk = hashlib.pbkdf2_hmac("sha1", pw, essid, 4096, 32)
    pmkid = hmac_mod.new(pmk, b"PMK Name" + ap + sta,
                         hashlib.sha1).digest()[:16]
    return f"{pmkid.hex()}*{ap.hex()}*{sta.hex()}*{essid.hex()}"


def test_pmkid_device_worker_end_to_end():
    """Planted passphrases in a 100-candidate keyspace, two essids."""
    engine = get_engine("wpa2-pmkid", device="jax")
    assert isinstance(engine, JaxPmkidEngine)
    engine.iterations = 256     # keep the CPU-backend test quick
    gen = MaskGenerator("secret?d?d")
    ap, sta = bytes.fromhex("aabbccddeeff"), bytes.fromhex("112233445566")

    def line(pw, essid):
        pmk = hashlib.pbkdf2_hmac("sha1", pw, essid, 256, 32)
        pmkid = hmac_mod.new(pmk, b"PMK Name" + ap + sta,
                             hashlib.sha1).digest()[:16]
        return f"{pmkid.hex()}*{ap.hex()}*{sta.hex()}*{essid.hex()}"

    cpu = get_engine("wpa2-pmkid", device="cpu")
    targets = [cpu.parse_target(line(b"secret42", b"NetA")),
               cpu.parse_target(line(b"secret87", b"NetB")),
               cpu.parse_target(line(b"secret87", b"NetA"))]
    w = PmkidDeviceWorker(engine, gen, targets, batch=32)
    hits = w.process(WorkUnit(0, 0, gen.keyspace))
    got = sorted((h.target_index, h.plaintext) for h in hits)
    assert got == [(0, b"secret42"), (1, b"secret87"), (2, b"secret87")]
    for h in hits:
        assert gen.candidate(h.cand_index) == h.plaintext


def test_jax_engine_registered_with_worker_factory():
    engine = get_engine("pmkid", device="jax")
    assert engine.salted
    assert hasattr(engine, "make_mask_worker")


def test_pallas_pmkid_worker_tpu_only_fallback(monkeypatch):
    """Off-TPU (this hermetic suite) the factory must return the XLA
    worker even when the kernel path is forced on -- the PBKDF2 kernel
    is TPU-only like the sha256 mask kernel (hardware proof:
    TPU_RESULTS_r04 / TPU_PROBE_LOG_r04)."""
    from dprf_tpu.engines.device.pmkid import (PallasPmkidWorker,
                                               PmkidDeviceWorker)
    from dprf_tpu.generators.mask import MaskGenerator

    monkeypatch.setenv("DPRF_PALLAS", "1")
    eng = get_engine("wpa2-pmkid", device="jax")
    t = eng.parse_target(
        "%s*0a1b2c3d4e5f*a0b1c2d3e4f5*%s" % ("ff" * 16,
                                            b"TestNet".hex()))
    w = eng.make_mask_worker(MaskGenerator("?l?l?l?l?l?l?l?l"), [t],
                             batch=4096, hit_capacity=8)
    assert isinstance(w, PmkidDeviceWorker)
    assert not isinstance(w, PallasPmkidWorker)


def test_pmkid_kernel_routing_heuristic(monkeypatch, caplog):
    """Many targets sharing one essid must stay on the XLA step (it
    amortizes the per-essid PBKDF2) -- checked with the backend gate
    neutralized so the heuristic itself is exercised."""
    from dprf_tpu.engines.device import pmkid as pmkid_mod
    from dprf_tpu.generators.mask import MaskGenerator

    eng = get_engine("wpa2-pmkid", device="jax")
    ts = [eng.parse_target(
        "%032x*0a1b2c3d4e5f*a0b1c2d3e4f%x*%s"
        % (i, i % 16, b"OneNet".hex())) for i in range(12)]
    # capture the decision reason: the heuristic must fire (logged
    # max_per_essid), not the backend gate
    logged = {}
    from dprf_tpu.utils import logging as dlog
    orig = dlog.DEFAULT.info
    monkeypatch.setattr(dlog.DEFAULT, "info",
                        lambda msg, **kw: logged.update(kw))
    w = pmkid_mod.maybe_pallas_pmkid_worker(
        eng, MaskGenerator("?l?l?l?l"), ts, batch=4096,
        hit_capacity=8, oracle=None)
    assert w is None
    assert logged.get("max_per_essid") == 12


def test_pmkid_lanes_matches_hashlib():
    """The kernel's shared pure body (pmkid_lanes) reproduces
    hashlib's PBKDF2-HMAC-SHA1 + HMAC PMKID bit-for-bit on an eager
    tiny batch -- key padding, chaining, PMK assembly, truncation.
    The pallas wrapper itself is hardware-proven (TPU_RESULTS_r04
    session5: planted crack at 4096 iterations)."""
    import hashlib as _hl
    import hmac as _hmac

    import jax.numpy as jnp

    from dprf_tpu.ops.pallas_pbkdf2 import pmkid_lanes

    essid, iters = b"TinyNet", 3
    ap, sta = bytes.fromhex("aabbccddeeff"), bytes.fromhex("112233445566")
    msg = b"PMK Name" + ap + sta
    msg_vals = [int(x) for x in np.frombuffer(msg, ">u4")]
    shape = (1, 128)
    # 128 distinct passphrases along the lanes, length 4
    import numpy as _np
    cands = [b"pw%02d" % i for i in range(100)] + [b"x%03d" % i
                                                   for i in range(28)]
    byts = [jnp.asarray(_np.array([c[p] for c in cands], _np.uint32)
                        .reshape(1, 128)) for p in range(4)]
    out = pmkid_lanes(byts, list(essid), len(essid), msg_vals,
                      jnp.int32(iters), shape)
    got = _np.stack([_np.asarray(w)[0] for w in out], axis=1)
    for lane_i in (0, 37, 99, 127):
        pmk = _hl.pbkdf2_hmac("sha1", cands[lane_i], essid, iters, 32)
        want = _np.frombuffer(
            _hmac.new(pmk, msg, _hl.sha1).digest()[:16], ">u4")
        assert (got[lane_i] == want).all(), lane_i


def test_pmkid_kernel_eligibility():
    from dprf_tpu.generators.mask import MaskGenerator
    from dprf_tpu.ops.pallas_pbkdf2 import pmkid_kernel_eligible

    g = MaskGenerator("?l?l?l?l?l?l?l?l")
    assert pmkid_kernel_eligible(g, [8, 12])
    assert not pmkid_kernel_eligible(g, [0])
    assert not pmkid_kernel_eligible(g, [40])
