"""Device PBKDF2-HMAC-SHA1 / WPA2-PMKID vs stdlib oracles.

Covers: RFC 6070 PBKDF2 vectors, random-candidate equivalence with
hashlib.pbkdf2_hmac, PMKID equivalence with the CPU oracle engine, and
the fused PMKID worker end-to-end (planted passphrase, multi-essid).
"""

import hashlib
import hmac as hmac_mod
import random

import jax.numpy as jnp
import numpy as np
import pytest

from dprf_tpu.engines import get_engine
from dprf_tpu.engines.device.pmkid import (JaxPmkidEngine,
                                           PmkidDeviceWorker)
from dprf_tpu.generators.mask import MaskGenerator
from dprf_tpu.ops import pack as pack_ops
from dprf_tpu.ops.hmac_sha1 import (hmac_key_states, hmac_sha1_20,
                                    pbkdf2_sha1_block, pbkdf2_sha1_pmk,
                                    pmkid_from_pmk)
from dprf_tpu.runtime.workunit import WorkUnit


def _pack_keys(keys: list) -> jnp.ndarray:
    maxlen = max(len(k) for k in keys)
    buf = np.zeros((len(keys), maxlen), dtype=np.uint8)
    for i, k in enumerate(keys):
        buf[i, :len(k)] = np.frombuffer(k, dtype=np.uint8)
    # zero padding beyond each key is exactly the HMAC key-block rule as
    # long as every key has the same length; tests use equal lengths.
    assert all(len(k) == maxlen for k in keys)
    return pack_ops.pack_raw(jnp.asarray(buf), maxlen, big_endian=True)


def _words_to_bytes(w: np.ndarray) -> bytes:
    return np.asarray(w).astype(">u4").tobytes()


def test_hmac_sha1_20_matches_stdlib():
    keys = [bytes([random.randrange(256) for _ in range(16)])
            for _ in range(32)]
    msg = bytes(range(20))
    kw = _pack_keys(keys)
    istate, ostate = hmac_key_states(kw)
    msg5 = jnp.broadcast_to(
        jnp.asarray(np.frombuffer(msg, dtype=">u4").astype(np.uint32)),
        (len(keys), 5))
    got = hmac_sha1_20(istate, ostate, msg5)
    for i, k in enumerate(keys):
        want = hmac_mod.new(k, msg, hashlib.sha1).digest()
        assert _words_to_bytes(got[i]) == want


@pytest.mark.parametrize("password,salt,iters,dk20", [
    # RFC 6070 test vectors (PBKDF2-HMAC-SHA1, dkLen=20)
    (b"password", b"salt", 1,
     "0c60c80f961f0e71f3a9b524af6012062fe037a6"),
    (b"password", b"salt", 2,
     "ea6c014dc72d6f8ccd1ed92ace1d41f0d8de8957"),
    (b"password", b"salt", 4096,
     "4b007901b765489abead49d926f721d065a429c1"),
])
def test_pbkdf2_rfc6070_vectors(password, salt, iters, dk20):
    kw = _pack_keys([password])
    istate, ostate = hmac_key_states(kw)
    t1 = pbkdf2_sha1_block(istate, ostate, salt, 1, iters)
    assert _words_to_bytes(t1[0]) == bytes.fromhex(dk20)


def test_pbkdf2_pmk_matches_hashlib():
    rng = random.Random(7)
    pws = [bytes(rng.randrange(0x21, 0x7F) for _ in range(10))
           for _ in range(8)]
    essid = b"TestNet-5G"
    got = pbkdf2_sha1_pmk(_pack_keys(pws), essid, iterations=128)
    for i, pw in enumerate(pws):
        want = hashlib.pbkdf2_hmac("sha1", pw, essid, 128, 32)
        assert _words_to_bytes(got[i]) == want


def test_full_4096_iteration_pmk():
    pw = b"password"
    essid = b"linksys"
    got = pbkdf2_sha1_pmk(_pack_keys([pw]), essid, iterations=4096)
    want = hashlib.pbkdf2_hmac("sha1", pw, essid, 4096, 32)
    assert _words_to_bytes(got[0]) == want


def test_pmkid_matches_cpu_oracle():
    oracle = get_engine("wpa2-pmkid", device="cpu")
    pw = b"hunter2hunter2"
    essid, ap, sta = b"CoffeeShop", bytes(range(6)), bytes(range(6, 12))
    pmk = hashlib.pbkdf2_hmac("sha1", pw, essid, 4096, 32)
    pmk_words = jnp.asarray(
        np.frombuffer(pmk, dtype=">u4").astype(np.uint32))[None, :]
    got = pmkid_from_pmk(pmk_words, ap, sta)
    want = oracle.hash_batch(
        [pw], params={"essid": essid, "mac_ap": ap, "mac_sta": sta})[0]
    assert _words_to_bytes(got[0]) == want


def _target_line(pw: bytes, essid: bytes, ap: bytes, sta: bytes) -> str:
    pmk = hashlib.pbkdf2_hmac("sha1", pw, essid, 4096, 32)
    pmkid = hmac_mod.new(pmk, b"PMK Name" + ap + sta,
                         hashlib.sha1).digest()[:16]
    return f"{pmkid.hex()}*{ap.hex()}*{sta.hex()}*{essid.hex()}"


def test_pmkid_device_worker_end_to_end():
    """Planted passphrases in a 100-candidate keyspace, two essids."""
    engine = get_engine("wpa2-pmkid", device="jax")
    assert isinstance(engine, JaxPmkidEngine)
    engine.iterations = 256     # keep the CPU-backend test quick
    gen = MaskGenerator("secret?d?d")
    ap, sta = bytes.fromhex("aabbccddeeff"), bytes.fromhex("112233445566")

    def line(pw, essid):
        pmk = hashlib.pbkdf2_hmac("sha1", pw, essid, 256, 32)
        pmkid = hmac_mod.new(pmk, b"PMK Name" + ap + sta,
                             hashlib.sha1).digest()[:16]
        return f"{pmkid.hex()}*{ap.hex()}*{sta.hex()}*{essid.hex()}"

    cpu = get_engine("wpa2-pmkid", device="cpu")
    targets = [cpu.parse_target(line(b"secret42", b"NetA")),
               cpu.parse_target(line(b"secret87", b"NetB")),
               cpu.parse_target(line(b"secret87", b"NetA"))]
    w = PmkidDeviceWorker(engine, gen, targets, batch=32)
    hits = w.process(WorkUnit(0, 0, gen.keyspace))
    got = sorted((h.target_index, h.plaintext) for h in hits)
    assert got == [(0, b"secret42"), (1, b"secret87"), (2, b"secret87")]
    for h in hits:
        assert gen.candidate(h.cand_index) == h.plaintext


def test_jax_engine_registered_with_worker_factory():
    engine = get_engine("pmkid", device="jax")
    assert engine.salted
    assert hasattr(engine, "make_mask_worker")
