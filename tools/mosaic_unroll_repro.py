"""Minimal repro for the Mosaic compile-helper SIGABRT on long
statically-unrolled gather/select chains (TPU_PROBE_LOG_r04 finding 9).

The krb5 kernel's unrolled 256-step RC4 KSA — each step one
per-sublane `take_along_axis` gather plus lane-iota selects on an
(SUB, 128) tile — crashes the remote `tpu_compile_helper` with SIGABRT
at every SUB tried, while the `lax.fori_loop` form of the SAME math
compiles in ~10 s.  This tool strips the repro to its skeleton: an
N-step unrolled chain of

    j   = (j + S[j]) & 255        # data-dependent per-sublane gather
    S   = select(lane == i%128, j, S)   # lane-iota "swap" write

with NOTHING else (no hashes, no key schedule, no second table half),
so the platform bug can be reported upstream and retried on newer
toolchains with one command.

Usage:
  python tools/mosaic_unroll_repro.py <steps> [sub]   # one point
  python tools/mosaic_unroll_repro.py --bisect [sub]  # smallest failing N

Each point runs in its OWN subprocess (the crash is a clean HTTP 500 /
SIGABRT per finding 9 — no tunnel wedge — but the client backend is
poisoned afterwards, so isolation is still mandatory).  Results append
to TPU_CASES_OUT (default /tmp/tpu_cases.jsonl) as
{"case": "unrollrepro-<steps>-<sub>", "ok": bool, ...}.
"""

import json
import os
import subprocess
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

OUT = os.environ.get("TPU_CASES_OUT", "/tmp/tpu_cases.jsonl")


def emit(doc):
    with open(OUT, "a") as f:
        f.write(json.dumps(doc) + "\n")


def run_point(steps: int, sub: int) -> dict:
    """Build + compile + run the N-step unrolled chain (in-process:
    callers isolate via subprocess)."""
    import jax
    import jax.numpy as jnp
    from jax import lax
    from jax.experimental import pallas as pl

    from dprf_tpu.utils.sync import hard_sync

    shape = (sub, 128)

    def kernel(out_ref):
        lane = lax.broadcasted_iota(jnp.int32, shape, 1)
        S = lane.astype(jnp.uint32)
        j = jnp.zeros(shape, jnp.uint32)
        for i in range(steps):          # the statically-unrolled chain
            idx7 = (j & jnp.uint32(127)).astype(jnp.int32)
            sj = jnp.take_along_axis(S, idx7, axis=1)
            j = (j + sj + jnp.uint32(i)) & jnp.uint32(255)
            S = jnp.where(lane == i % 128, j, S)
        out_ref[...] = S[:8] if sub >= 8 else jnp.broadcast_to(
            S[:1], (8, 128))

    fn = pl.pallas_call(
        kernel,
        out_specs=[pl.BlockSpec((8, 128), lambda: (0, 0))],
        out_shape=[jax.ShapeDtypeStruct((8, 128), jnp.uint32)],
    )
    t0 = time.perf_counter()
    (out,) = fn()
    hard_sync(out)
    return {"compile_run_s": round(time.perf_counter() - t0, 1)}


def run_isolated(steps: int, sub: int, timeout_s: int = 420) -> dict:
    """One (steps, sub) point in a child process; never killed early
    unless it exceeds timeout_s (compile hangs are finding-8 territory
    and the caller should stop bisecting immediately)."""
    case = f"unrollrepro-{steps}-{sub}"
    code = (f"import sys; sys.path.insert(0, {os.path.dirname(os.path.dirname(os.path.abspath(__file__)))!r});"
            f"from tools.mosaic_unroll_repro import run_point;"
            f"import json; print('REPRO_JSON:' + json.dumps(run_point({steps}, {sub})))")
    t0 = time.time()
    try:
        proc = subprocess.run([sys.executable, "-c", code],
                              capture_output=True, text=True,
                              timeout=timeout_s)
    except subprocess.TimeoutExpired:
        doc = {"case": case, "ok": False, "outcome": "TIMEOUT",
               "timeout_s": timeout_s,
               "warning": "possible compile HANG (finding-8 class): "
                          "stop probing, check tunnel health"}
        emit(doc)
        return doc
    outcome, extra = "CRASH", {}
    for line in proc.stdout.splitlines():
        if line.startswith("REPRO_JSON:"):
            outcome = "OK"
            extra = json.loads(line[len("REPRO_JSON:"):])
    doc = {"case": case, "ok": outcome == "OK", "outcome": outcome,
           "rc": proc.returncode, "elapsed_s": round(time.time() - t0, 1),
           **extra}
    if outcome == "CRASH":
        doc["stderr_tail"] = proc.stderr[-500:]
    emit(doc)
    return doc


def bisect(sub: int) -> None:
    """Smallest failing step count in [2, 256] (lo always compiles,
    hi is the known-SIGABRT production shape)."""
    lo, hi = 2, 256            # invariant: lo OK, hi CRASH (verified)
    d = run_isolated(hi, sub)
    if d["ok"]:
        print(json.dumps({"result": "256-step chain now COMPILES -- "
                          "toolchain fixed? re-enable DPRF_KRB5_UNROLL "
                          "and re-measure", "sub": sub}))
        return
    if d["outcome"] == "TIMEOUT":
        return
    d = run_isolated(lo, sub)
    if d["outcome"] == "TIMEOUT":
        return                # finding-8-class hang: stop probing
    if not d["ok"]:
        print(json.dumps({"result": "even 2 steps fail", "sub": sub}))
        return
    while hi - lo > 1:
        mid = (lo + hi) // 2
        d = run_isolated(mid, sub)
        if d["outcome"] == "TIMEOUT":
            return
        lo, hi = (mid, hi) if d["ok"] else (lo, mid)
        print(f"bisect: OK<= {lo}, CRASH>= {hi}", file=sys.stderr)
    print(json.dumps({"result": "minimal failing unroll length",
                      "sub": sub, "last_ok": lo, "first_crash": hi}))
    emit({"case": f"unrollrepro-bisect-{sub}", "ok": True,
          "last_ok": lo, "first_crash": hi})


def main():
    if sys.argv[1] == "--bisect":
        bisect(int(sys.argv[2]) if len(sys.argv) > 2 else 32)
    else:
        steps = int(sys.argv[1])
        sub = int(sys.argv[2]) if len(sys.argv) > 2 else 32
        print(json.dumps(run_isolated(steps, sub)))


if __name__ == "__main__":
    main()
