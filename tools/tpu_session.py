"""One TPU-client session: Mosaic lowering proof + kernel benches.

The axon tunnel serves ONE client at a time and wedges if a client is
killed mid-handshake (see tools/tpu_probe.py).  So this script does all
real-TPU work for a round in a single process, reports progress through
a status file (atomic replace, poll it -- NEVER kill this process), and
exits cleanly whatever happens.

Stages:
  1. lowering -- compile + run every Pallas kernel variant on the real
     chip with a planted target; record compile time and correctness.
  2. bench    -- sustained H/s for the MD5 kernel and the XLA pipeline
     (the BENCH north-star paths), plus NTLM multi-target and SHA-256.

Results land in TPU_SESSION_OUT (default /tmp/tpu_session_results.json).
"""

import json
import os
import sys
import time
import traceback

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

STATUS = os.environ.get("TPU_SESSION_STATUS", "/tmp/tpu_session_status.json")
OUT = os.environ.get("TPU_SESSION_OUT", "/tmp/tpu_session_results.json")

RESULTS = {"stages": {}, "started": time.time()}


def write_status(stage, **kw):
    tmp = STATUS + ".tmp"
    with open(tmp, "w") as f:
        json.dump({"stage": stage, "t": time.time(), **kw}, f)
        f.write("\n")
    os.replace(tmp, STATUS)


def flush_results():
    tmp = OUT + ".tmp"
    with open(tmp, "w") as f:
        json.dump(RESULTS, f, indent=1)
        f.write("\n")
    os.replace(tmp, OUT)


def plant_target(engine_name, gen, index):
    """CPU-oracle digest words for the candidate at `index`."""
    import numpy as np
    from dprf_tpu import get_engine
    oracle = get_engine(engine_name, device="cpu")
    cand = gen.candidate(index)
    digest = oracle.hash_batch([cand])[0]
    dt = "<u4" if engine_name in ("md5", "ntlm") else ">u4"
    return np.frombuffer(digest, dtype=dt).astype(np.uint32), cand


def check_lowering():
    import numpy as np
    import jax
    from dprf_tpu.generators.mask import MaskGenerator
    from dprf_tpu.ops import pallas_mask as pm

    cases = [
        ("md5", "?l?l?l?l?l?l", 1),
        ("sha1", "?l?l?l?l?l?l", 1),
        ("ntlm", "?a?a?a?a?a?a?a", 1),
        ("sha256", "?l?l?l?l?l?l?l?l", 1),
        ("md5", "?a?a?a?a?a?a?a", 1000),   # Bloom multi-target gather
        ("ntlm", "?a?a?a?a?a?a?a", 1000),
    ]
    out = {}
    for engine, mask, n_targets in cases:
        name = f"{engine}/{n_targets}t"
        write_status("lowering", case=name)
        rec = {"engine": engine, "mask": mask, "targets": n_targets}
        try:
            gen = MaskGenerator(mask)
            batch = pm.TILE * 4
            plant_idx = pm.TILE + 7   # tile 1, lane 7
            tw, cand = plant_target(engine, gen, plant_idx)
            if n_targets > 1:
                rng = np.random.RandomState(42)
                tws = rng.randint(0, 2**32, (n_targets, tw.shape[0]),
                                  dtype=np.uint32)
                tws[313] = tw   # bury the real target mid-list
                tw = tws
            t0 = time.perf_counter()
            fn = pm.make_mask_pallas_fn(engine, gen, tw, batch)
            import jax.numpy as jnp
            base = jnp.asarray(gen.digits(0), jnp.int32)
            counts, lanes = jax.block_until_ready(
                fn(base, jnp.asarray([batch], jnp.int32)))
            rec["compile_s"] = round(time.perf_counter() - t0, 2)
            counts = np.asarray(counts)[:, 0]
            lanes = np.asarray(lanes)[:, 0]
            hits = [(t * pm.TILE + lanes[t]) for t in np.nonzero(counts)[0]]
            if n_targets > 1:
                # multi-target counts are Bloom MAYBE counts: the planted
                # hit must be present; a stray false maybe (p ~ 1.5e-5 per
                # lane) is tolerated, not a failure.
                rec["ok"] = (plant_idx in hits and int(counts.sum()) <= 3)
            else:
                rec["ok"] = (int(counts.sum()) == 1 and hits == [plant_idx])
            rec["hits"] = [int(h) for h in hits]
            if not rec["ok"]:
                rec["counts_nonzero"] = int((counts > 0).sum())
        except Exception as e:  # record, keep going
            rec["ok"] = False
            rec["error"] = f"{type(e).__name__}: {e}"
            rec["traceback"] = traceback.format_exc()[-1500:]
        out[name] = rec
        RESULTS["stages"]["lowering"] = out
        flush_results()
    return out


from dprf_tpu.bench import calibrated_inner as _calibrated_inner


def bench_all():
    """Each case: calibrate with a short inner loop (one dispatch, so
    the ~0.4 s/round-trip tunnel latency can't dominate), then measure
    ~3 dispatches at a ~5 s inner loop.  run_bench(inner=...) does the
    device-side looping."""
    from dprf_tpu.bench import run_bench
    out = {}
    runs = [
        ("md5-pallas", dict(engine="md5", impl="pallas", batch=1 << 22)),
        ("md5-xla", dict(engine="md5", impl="xla", batch=1 << 22)),
        ("ntlm-pallas", dict(engine="ntlm", impl="pallas",
                             mask="?a?a?a?a?a?a?a", batch=1 << 22)),
        ("sha1-pallas", dict(engine="sha1", impl="pallas", batch=1 << 22)),
        ("sha256-pallas", dict(engine="sha256", impl="pallas",
                               batch=1 << 22)),
        ("sha256-xla", dict(engine="sha256", impl="xla", batch=1 << 21)),
    ]
    for name, kw in runs:
        write_status("bench", case=name, phase="calibrate")
        try:
            cal = run_bench(device="jax", seconds=0.1, inner=16, **kw)
            inner = _calibrated_inner(cal["value"], kw["batch"])
            write_status("bench", case=name, phase="measure",
                         inner=inner, cal_hs=cal["value"])
            out[name] = run_bench(device="jax", seconds=15.0,
                                  inner=inner, **kw)
            out[name]["calibrate_hs"] = cal["value"]
        except Exception as e:
            out[name] = {"error": f"{type(e).__name__}: {e}",
                         "traceback": traceback.format_exc()[-1500:]}
        RESULTS["stages"]["bench"] = out
        flush_results()
    return out


def sweep_sub():
    """Raw kernel throughput vs SUB (sublanes per grid cell): the main
    tuning knob.  Times the bare pallas fn (no worker machinery) on an
    unmatchable target, with a device-side fori_loop per dispatch so
    tunnel latency can't mask the differences between SUB values."""
    import numpy as np
    import jax
    import jax.numpy as jnp
    from jax import lax
    from dprf_tpu.generators.mask import MaskGenerator
    from dprf_tpu.ops import pallas_mask as pm

    gen = MaskGenerator("?a?a?a?a?a?a?a?a")
    tw = np.full((4,), 0xFFFFFFFF, np.uint32)   # unmatchable
    out = {}
    for sub in (8, 16, 32, 64, 128):
        name = f"sub{sub}"
        write_status("sweep", case=name)
        try:
            tile = sub * 128
            batch = (max(1 << 22, tile) // tile) * tile
            fn = pm.make_mask_pallas_fn("md5", gen, tw, batch, sub=sub)
            nv = jnp.asarray([batch], jnp.int32)

            def looped(inner, fn=fn, nv=nv):
                @jax.jit
                def run(base):
                    def body(i, acc):
                        c, l = fn(base.at[-1].add(i), nv)
                        return acc + c.sum() + l.sum()
                    return lax.fori_loop(0, inner, body, jnp.int32(0))
                return run

            base = jnp.asarray(gen.digits(0), jnp.int32)
            # calibrate: compile first, then time ONE 16-iter dispatch
            # (timing the compile here would collapse `inner` and
            # re-measure tunnel latency -- the bug this sweep fixes)
            cal = looped(16)
            jax.block_until_ready(cal(base))
            t0 = time.perf_counter()
            jax.block_until_ready(cal(base))
            cal_s = time.perf_counter() - t0
            rate = 16 * batch / max(cal_s, 1e-3)
            inner = _calibrated_inner(rate, batch)
            run = looped(inner)
            jax.block_until_ready(run(base))       # compile
            n, t0 = 0, time.perf_counter()
            while time.perf_counter() - t0 < 10.0:
                jax.block_until_ready(run(base))
                n += 1
            dt = time.perf_counter() - t0
            out[name] = {"sub": sub, "hs": n * inner * batch / dt,
                         "batch": batch, "inner": inner,
                         "dispatches": n, "cal_hs": rate}
        except Exception as e:
            out[name] = {"sub": sub,
                         "error": f"{type(e).__name__}: {e}"}
        RESULTS["stages"]["sweep"] = out
        flush_results()
    return out


def bench_slow_engines():
    """The iterated/memory-hard acceptance paths (configs 4/5 + scrypt)
    measured as raw fused steps with device-side loops.  Each step's
    own iteration structure (fori_loop x 4096 for PBKDF2, 2^cost
    EksBlowfish rounds, N BlockMix rounds) already amortizes dispatch
    latency, but the looped wrapper still batches a few steps per
    round trip."""
    import numpy as np
    import jax
    import jax.numpy as jnp
    from jax import lax

    from dprf_tpu import get_engine
    from dprf_tpu.generators.mask import MaskGenerator

    out = {}

    def timed(name, fn, base, per_dispatch, seconds=15.0):
        t0 = time.perf_counter()
        jax.block_until_ready(fn(base))
        compile_s = time.perf_counter() - t0
        n, t0 = 0, time.perf_counter()
        while time.perf_counter() - t0 < seconds:
            jax.block_until_ready(fn(base))
            n += 1
        dt = time.perf_counter() - t0
        out[name] = {"hs": n * per_dispatch / dt,
                     "per_dispatch": per_dispatch, "dispatches": n,
                     "compile_s": round(compile_s, 1),
                     "elapsed_s": round(dt, 2)}

    # -- PMKID (config 5): PBKDF2-HMAC-SHA1 x 4096 + PMKID compare
    write_status("slow", case="pmkid")
    try:
        from dprf_tpu.engines.device.pmkid import make_pmkid_crack_step
        eng = get_engine("wpa2-pmkid", device="jax")
        tgt = eng.parse_target(
            "%s*0a1b2c3d4e5f*a0b1c2d3e4f5*%s" % ("ff" * 16,
                                                b"benchnet".hex()))
        gen = MaskGenerator("?l?l?l?l?l?l?l?l")
        B = 1 << 12
        step = make_pmkid_crack_step(eng, gen, [tgt], B)

        @jax.jit
        def run(base):
            def body(i, acc):
                o = step(base.at[-1].add(i), jnp.int32(B))
                return acc + o[0]
            return lax.fori_loop(0, 4, body, jnp.int32(0))

        timed("pmkid", run, jnp.asarray(gen.digits(0), jnp.int32), 4 * B)
    except Exception as e:
        out["pmkid"] = {"error": f"{type(e).__name__}: {e}",
                        "traceback": traceback.format_exc()[-1200:]}
    RESULTS["stages"]["slow"] = out
    flush_results()

    # -- LM / bitslice DES (fast-hash class; here because it shares
    # the custom-loop harness)
    write_status("slow", case="lm")
    try:
        from dprf_tpu.engines.device.lm import make_lm_mask_step
        from dprf_tpu.engines.base import Target
        gen = MaskGenerator("?u?u?u?u?u?u?u")
        B = 1 << 20
        tgt = Target(raw="bench", digest=bytes(8))   # unmatchable-ish
        step = make_lm_mask_step(gen, [tgt], B)

        @jax.jit
        def run(base):
            def body(i, acc):
                o = step(base.at[-1].add(i), jnp.int32(B))
                return acc + o[0]
            return lax.fori_loop(0, 64, body, jnp.int32(0))

        timed("lm", run, jnp.asarray(gen.digits(0), jnp.int32), 64 * B)
    except Exception as e:
        out["lm"] = {"error": f"{type(e).__name__}: {e}",
                     "traceback": traceback.format_exc()[-1200:]}
    RESULTS["stages"]["slow"] = out
    flush_results()

    # -- scrypt 16384:8:1 (the common interactive parameter set)
    write_status("slow", case="scrypt")
    try:
        from dprf_tpu.ops.hmac import pack_raw_varlen
        from dprf_tpu.ops.scrypt import scrypt_dk
        gen = MaskGenerator("?l?l?l?l?l?l?l?l")
        B = 1 << 8           # V = B * 16 MiB = 4 GiB HBM
        flat = gen.flat_charsets

        @jax.jit
        def run(base):
            cand = gen.decode_batch(base, flat, B)
            kw = pack_raw_varlen(cand, jnp.full((B,), 8, jnp.int32),
                                 True)
            salt = jnp.zeros((51,), jnp.uint8)
            dk = scrypt_dk(kw, salt, jnp.int32(8), 16384, 8, 1)
            return dk.sum()

        timed("scrypt", run, jnp.asarray(gen.digits(0), jnp.int32), B,
              seconds=30.0)
    except Exception as e:
        out["scrypt"] = {"error": f"{type(e).__name__}: {e}",
                         "traceback": traceback.format_exc()[-1200:]}
    RESULTS["stages"]["slow"] = out
    flush_results()
    # -- bcrypt (config 4's path) at cost 8: the S-box gathers
    # serialize with batch AND rounds, so a cost-12 dispatch (~218 s)
    # exceeds the tunnel's ~60 s execution deadline at any batch and
    # faults the whole client backend (measured 2026-07-30); cost 8 at
    # B=64 (~14 s dispatches) measures the same code path safely --
    # scale the number by 1/16 for the cost-12 figure.
    write_status("slow", case="bcrypt8")
    try:
        from dprf_tpu.engines.device.bcrypt import make_bcrypt_mask_step
        gen = MaskGenerator("?l?l?l?l?l?l")
        B = 64
        step = make_bcrypt_mask_step(gen, B)
        salt_words = jnp.asarray(
            np.frombuffer(bytes(range(16)), ">u4").astype(np.uint32))
        tgt = jnp.full((6,), 0xFFFFFFFF, jnp.uint32)

        @jax.jit
        def run(base):
            o = step(base, jnp.int32(B), salt_words,
                     jnp.int32(1 << 8), tgt)
            return o[0]

        timed("bcrypt8", run, jnp.asarray(gen.digits(0), jnp.int32), B,
              seconds=30.0)
    except Exception as e:
        out["bcrypt8"] = {"error": f"{type(e).__name__}: {e}",
                         "traceback": traceback.format_exc()[-1200:]}
    RESULTS["stages"]["slow"] = out
    flush_results()

    return out


def main():
    write_status("starting", pid=os.getpid())
    import jax
    devs = jax.devices()
    RESULTS["devices"] = [str(d) for d in devs]
    RESULTS["platform"] = devs[0].platform
    write_status("devices", devices=RESULTS["devices"])
    flush_results()
    if devs[0].platform != "tpu":
        write_status("done", ok=False, note="no TPU")
        return 1
    check_lowering()
    sweep_sub()
    bench_all()
    bench_slow_engines()
    RESULTS["finished"] = time.time()
    flush_results()
    write_status("done", ok=True)
    print("TPU session complete")
    return 0


if __name__ == "__main__":
    sys.exit(main())
