"""TPU measurement session: an orchestrator + per-stage client children.

The axon tunnel serves ONE client at a time and wedges if a client is
killed mid-handshake (tools/tpu_probe.py).  Round 3 ran all stages in
one client process with manual case ordering, and a bcrypt kernel
fault poisoned the in-process backend and corrupted the following
cases (TPU_PROBE_LOG_r03 session W1).  This round EVERY stage runs in
its own child process (VERDICT r3 #6):

  - the parent NEVER imports jax (it must not hold the single client
    slot) -- it spawns `tpu_session.py --child STAGE`, polls the
    stage's result file, and merges results;
  - children exit cleanly whatever happens, releasing the slot;
  - nothing is ever killed -- a hung child is abandoned after its
    deadline (recorded as timeout) and the next child simply tries to
    connect;
  - each finished stage is scanned for the poisoned-backend signature
    (physically impossible rates; "TPU device error" strings) and
    flagged, so one faulting stage leaves a visible mark instead of
    silently corrupting the session.

Stages (each one client process):
  kernels    -- Mosaic lowering + planted-target proof for all Pallas
                kernel variants
  bench_fast -- sustained H/s for the md5/ntlm/sha1/sha256 kernels and
                the XLA pipeline (the BENCH north-star paths)
  config1..5 -- the five BASELINE.json acceptance workloads through
                the REAL worker paths (dprf_tpu.bench.run_config);
                config 4 uses the deadline-bounded chunked bcrypt
                protocol at a small batch (cost 12 is ~0.3 H/s -- the
                batch IS the time budget)
  sweep      -- SUB tuning sweep (opt-in; SUB=128 is the r3 winner)

Usage:
  python tools/tpu_session.py                  # default round plan
  python tools/tpu_session.py kernels config3  # just those stages
  python tools/tpu_session.py --child STAGE --out PATH   # internal
"""

import json
import os
import subprocess
import sys
import time
import traceback

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

STATUS = os.environ.get("TPU_SESSION_STATUS", "/tmp/tpu_session_status.json")
OUT = os.environ.get("TPU_SESSION_OUT", "/tmp/tpu_session_results.json")
WORKDIR = os.environ.get("TPU_SESSION_WORKDIR", "/tmp/tpu_session_stages")

#: per-stage wall deadlines (compile + measure + tunnel RTTs), seconds.
#: Children are ABANDONED (never killed) past the deadline.
DEADLINES = {
    "kernels": 900,
    "bench_fast": 1500,
    "bench_r4b": 1500,
    "config1": 600,
    "config2": 600,
    "config3": 900,
    "config4": 900,
    "config5": 900,
    "sweep": 1200,
    "ext_kernels": 1800,
    "rules_kernel": 1200,
}

#: deadlines for "case:<kind>-..." stages, by case kind: the slow /
#: memory-hard kinds need compile + multi-minute dispatch chains.
CASE_DEADLINES = {
    "bcryptchunk": 1800, "pallaseks": 1800, "scrypt": 1500,
    "bcrypt": 1200, "descrypt": 900, "pmkid": 1200,
    "scanprobe": 900, "superstep": 900, "krb5": 1200,
    "krb5cfg": 900, "pdf": 1200, "sevenzip": 1500,
}


def stage_deadline(stage: str) -> int:
    if stage.startswith("case:"):
        kind = stage[len("case:"):].split("-")[0]
        return CASE_DEADLINES.get(kind, 900)
    return DEADLINES.get(stage, 600)


DEFAULT_PLAN = ["kernels", "bench_fast", "config1", "config2", "config3",
                "config5", "config4"]   # bcrypt last: slowest, riskiest

#: a single-chip rate above this is physically impossible for any
#: engine here (md5 roofline ~8e9 H/s; see BASELINE.md) -- it is the
#: signature of a dead backend completing dispatches with poisoned
#: buffers, or of enqueue-speed timing (utils/sync.py).
POISON_RATE = 5e10


# ---------------------------------------------------------------- children

def _atomic_write(path, doc):
    tmp = path + ".tmp"
    with open(tmp, "w") as f:
        json.dump(doc, f, indent=1)
        f.write("\n")
    os.replace(tmp, path)


class StageIO:
    """Progress + result reporting for one child stage."""

    def __init__(self, name, out_path):
        self.name = name
        self.out_path = out_path
        self.doc = {"stage": name, "started": time.time(),
                    "results": {}, "done": False}

    def status(self, case, **kw):
        self.doc["now"] = {"case": case, "t": time.time(), **kw}
        _atomic_write(self.out_path, self.doc)

    def record(self, case, result):
        self.doc["results"][case] = result
        _atomic_write(self.out_path, self.doc)

    def finish(self, ok=True, **kw):
        self.doc["done"] = True
        self.doc["ok"] = ok
        self.doc["finished"] = time.time()
        self.doc.update(kw)
        _atomic_write(self.out_path, self.doc)


def _plant_target(engine_name, gen, index):
    """CPU-oracle digest words for the candidate at `index`."""
    import numpy as np

    from dprf_tpu import get_engine
    oracle = get_engine(engine_name, device="cpu")
    cand = gen.candidate(index)
    digest = oracle.hash_batch([cand])[0]
    dt = "<u4" if engine_name in ("md5", "ntlm") else ">u4"
    return np.frombuffer(digest, dtype=dt).astype(np.uint32), cand


def stage_kernels(io: StageIO):
    """Compile + run every Pallas kernel variant with a planted target.

    One harness for both kernel families: a case supplies its factory
    (fn(gen, tw, batch) -> pallas fn) and tile size; the MD factories
    come from pallas_mask, the sponge factories from pallas_keccak."""
    import numpy as np
    import jax.numpy as jnp

    from dprf_tpu.generators.mask import MaskGenerator
    from dprf_tpu.ops import pallas_keccak as pk
    from dprf_tpu.ops import pallas_mask as pm
    from dprf_tpu.utils.sync import hard_sync

    def md(engine):
        return (lambda gen, tw, batch:
                pm.make_mask_pallas_fn(engine, gen, tw, batch)), pm.TILE

    def keccak(pad, rate, outb):
        return (lambda gen, tw, batch:
                pk.make_keccak_pallas_fn(gen, tw, batch, pad, rate,
                                         outb)), pk.SUBK * 128

    cases = [
        ("md5", "?l?l?l?l?l?l", 1, *md("md5")),
        ("sha1", "?l?l?l?l?l?l", 1, *md("sha1")),
        ("ntlm", "?a?a?a?a?a?a?a", 1, *md("ntlm")),
        ("sha256", "?l?l?l?l?l?l?l?l", 1, *md("sha256")),
        ("sha512", "?l?l?l?l?l?l?l?l", 1, *md("sha512")),   # r4b
        ("sha384", "?l?l?l?l?l?l?l?l", 1, *md("sha384")),
        ("md5", "?a?a?a?a?a?a?a", 1000, *md("md5")),   # Bloom multi
        ("ntlm", "?a?a?a?a?a?a?a", 1000, *md("ntlm")),
        ("sha512", "?a?a?a?a?a?a?a", 1000, *md("sha512")),
        # r4b sponge kernels (own factory: not MD framing)
        ("sha3-256", "?l?l?l?l?l?l", 1, *keccak(0x06, 136, 32)),
        ("keccak-256", "?l?l?l?l?l?l", 1, *keccak(0x01, 136, 32)),
        ("sha3-512", "?l?l?l?l?l?l", 1, *keccak(0x06, 72, 64)),
    ]
    for engine, mask, n_targets, factory, tile in cases:
        name = f"{engine}/{n_targets}t"
        io.status(name)
        rec = {"engine": engine, "mask": mask, "targets": n_targets}
        try:
            gen = MaskGenerator(mask)
            batch = tile * 4
            plant_idx = tile + 7   # tile 1, lane 7
            tw, _ = _plant_target(engine, gen, plant_idx)
            if n_targets > 1:
                rng = np.random.RandomState(42)
                tws = rng.randint(0, 2**32, (n_targets, tw.shape[0]),
                                  dtype=np.uint32)
                tws[313] = tw   # bury the real target mid-list
                tw = tws
            t0 = time.perf_counter()
            fn = factory(gen, tw, batch)
            base = jnp.asarray(gen.digits(0), jnp.int32)
            out = fn(base, jnp.asarray([batch], jnp.int32))
            hard_sync(out)
            rec["compile_s"] = round(time.perf_counter() - t0, 2)
            counts = np.asarray(out[0])[:, 0]
            lanes = np.asarray(out[1])[:, 0]
            hits = [(t * tile + lanes[t]) for t in np.nonzero(counts)[0]]
            if n_targets > 1:
                # multi-target counts are Bloom MAYBE counts: the
                # planted hit must be present; a stray false maybe
                # (p ~ 1.5e-5/lane) is tolerated, not a failure
                rec["ok"] = (plant_idx in hits and int(counts.sum()) <= 3)
            else:
                rec["ok"] = (int(counts.sum()) == 1 and hits == [plant_idx])
            rec["hits"] = [int(h) for h in hits]
        except Exception as e:   # record, keep going
            rec["ok"] = False
            rec["error"] = f"{type(e).__name__}: {e}"
            rec["traceback"] = traceback.format_exc()[-1500:]
        io.record(name, rec)


def stage_bench_fast(io: StageIO):
    """Sustained kernel/pipeline H/s (run_bench does honest hard_sync
    timing internally)."""
    runs = [
        ("md5-pallas", dict(engine="md5", impl="pallas", batch=1 << 22)),
        ("md5-xla", dict(engine="md5", impl="xla", batch=1 << 22)),
        ("ntlm-pallas", dict(engine="ntlm", impl="pallas",
                             mask="?a?a?a?a?a?a?a", batch=1 << 22)),
        ("sha1-pallas", dict(engine="sha1", impl="pallas", batch=1 << 22)),
        ("sha256-pallas", dict(engine="sha256", impl="pallas",
                               batch=1 << 22)),
        ("sha256-xla", dict(engine="sha256", impl="xla", batch=1 << 21)),
    ]
    _run_bench_list(io, runs)


def _run_bench_list(io: StageIO, runs) -> None:
    """Calibrate+measure each (name, run_bench kwargs) pair, recording
    errors per case so one failure doesn't sink the stage."""
    for name, kw in runs:
        io.status(name, phase="calibrate+measure")
        try:
            res = _calibrated_bench(**kw)
        except Exception as e:
            res = {"error": f"{type(e).__name__}: {e}",
                   "traceback": traceback.format_exc()[-1500:]}
        io.record(name, res)


def stage_bench_r4b(io: StageIO):
    """Round-4b kernel families (SHA-512/384 pair-arithmetic cores,
    Keccak/SHA3 sponge kernels) plus their XLA pipelines for the
    speedup denominator -- the BASELINE.md 'round 4b additions'
    predictions, measured."""
    runs = [
        ("sha512-pallas", dict(engine="sha512", impl="pallas",
                               batch=1 << 22)),
        ("sha384-pallas", dict(engine="sha384", impl="pallas",
                               batch=1 << 22)),
        ("sha512-xla", dict(engine="sha512", impl="xla", batch=1 << 20)),
        ("sha3-256-pallas", dict(engine="sha3-256", impl="pallas",
                                 batch=1 << 22)),
        ("keccak-256-pallas", dict(engine="keccak-256", impl="pallas",
                                   batch=1 << 22)),
        ("sha3-512-pallas", dict(engine="sha3-512", impl="pallas",
                                 batch=1 << 22)),
        ("sha3-256-xla", dict(engine="sha3-256", impl="xla",
                              batch=1 << 20)),
    ]
    _run_bench_list(io, runs)


#: per-config run_config kwargs: batch sized so one worker stride is
#: seconds (fast engines) or one deadline-safe chunked batch (bcrypt).
CONFIG_ARGS = {
    # unit_strides sized for ~60-200 ms of compute per WorkUnit so the
    # one-readback-per-unit worker path amortizes the ~60 ms tunnel RTT
    1: dict(seconds=15.0, batch=1 << 22, unit_strides=64),
    2: dict(seconds=15.0, batch=1 << 22, unit_strides=256),
    3: dict(seconds=20.0, batch=1 << 20, unit_strides=64),
    # cost 12 at ~0.3 H/s: one 64-lane chunked batch is ~3.5 min of
    # deadline-bounded dispatches; seconds only gates NEW strides
    4: dict(seconds=1.0, batch=64, bcrypt_cost=12),
    5: dict(seconds=20.0, batch=1 << 14, unit_strides=8),
}


def _stage_config(n):
    def run(io: StageIO):
        from dprf_tpu.bench import run_config
        io.status(f"config{n}")
        res = run_config(n, device="jax", **CONFIG_ARGS[n])
        io.record(f"config{n}", res)
    run.__name__ = f"stage_config{n}"
    return run


def stage_sweep(io: StageIO):
    """Raw kernel throughput vs SUB (sublanes per grid cell)."""
    import numpy as np
    import jax
    import jax.numpy as jnp
    from jax import lax

    from dprf_tpu.bench import calibrated_inner
    from dprf_tpu.generators.mask import MaskGenerator
    from dprf_tpu.ops import pallas_mask as pm
    from dprf_tpu.utils.sync import hard_sync

    gen = MaskGenerator("?a?a?a?a?a?a?a?a")
    tw = np.full((4,), 0xFFFFFFFF, np.uint32)   # unmatchable
    for sub in (8, 16, 32, 64, 128):
        name = f"sub{sub}"
        io.status(name)
        try:
            tile = sub * 128
            batch = (max(1 << 22, tile) // tile) * tile
            fn = pm.make_mask_pallas_fn("md5", gen, tw, batch, sub=sub)
            nv = jnp.asarray([batch], jnp.int32)

            def looped(inner, fn=fn, nv=nv):
                @jax.jit
                def run(base):
                    def body(i, acc):
                        c, l = fn(base.at[-1].add(i), nv)
                        return acc + c.sum() + l.sum()
                    return lax.fori_loop(0, inner, body, jnp.int32(0))
                return run

            base = jnp.asarray(gen.digits(0), jnp.int32)
            cal = looped(16)
            hard_sync(cal(base))            # compile
            t0 = time.perf_counter()
            hard_sync(cal(base))
            cal_s = time.perf_counter() - t0
            rate = 16 * batch / max(cal_s, 1e-3)
            inner = calibrated_inner(rate, batch)
            run = looped(inner)
            hard_sync(run(base))            # compile
            n, t0 = 0, time.perf_counter()
            while time.perf_counter() - t0 < 10.0:
                hard_sync(run(base))
                n += 1
            dt = time.perf_counter() - t0
            io.record(name, {"sub": sub, "hs": n * inner * batch / dt,
                             "batch": batch, "inner": inner,
                             "dispatches": n, "cal_hs": rate})
        except Exception as e:
            io.record(name, {"sub": sub,
                             "error": f"{type(e).__name__}: {e}"})


def _calibrated_bench(**kw):
    """Shared calibrate-then-measure sequence (see stage_bench_fast):
    a 0.1 s / inner=16 probe sizes the device loop, then a 15 s
    measured run."""
    from dprf_tpu.bench import calibrated_inner, run_bench
    cal = run_bench(device="jax", seconds=0.1, inner=16, **kw)
    inner = calibrated_inner(cal["value"], kw["batch"])
    res = run_bench(device="jax", seconds=15.0, inner=inner, **kw)
    res["calibrate_hs"] = cal["value"]
    return res


def _prove_planted(io: StageIO, name: str, plant: int, salt=None,
                   expected_worker: str = "PallasMaskWorker"):
    """Plant one target in a small mask keyspace, build the production
    worker, and verify it is the expected kernel worker AND cracks
    exactly the plant."""
    from dprf_tpu import get_engine
    from dprf_tpu.generators.mask import MaskGenerator
    from dprf_tpu.runtime.workunit import WorkUnit

    io.status(f"lower/{name}")
    rec = {"variant": name}
    if salt is not None:
        rec["salt_len"] = len(salt)
    try:
        gen = MaskGenerator("?l?l?l?l?l")
        cpu = get_engine(name, device="cpu")
        dev = get_engine(name, device="jax")
        params = {"salt": salt} if salt is not None else None
        d = cpu.hash_batch([gen.candidate(plant)], params=params)[0]
        if salt is not None:
            tgt = cpu.parse_target(d.hex() + ":" + salt.decode())
        else:
            tgt = cpu.parse_target(d.hex() if name != "mysql41"
                                   else "*" + d.hex().upper())
        t0 = time.perf_counter()
        w = dev.make_mask_worker(gen, [tgt], batch=1 << 20,
                                 hit_capacity=8, oracle=cpu)
        rec["worker"] = type(w).__name__
        rec["compile_s"] = round(time.perf_counter() - t0, 2)
        hits = w.process(WorkUnit(0, 0, gen.keyspace))
        rec["ok"] = ([(h.target_index, h.cand_index) for h in hits]
                     == [(0, plant)]
                     and rec["worker"] == expected_worker)
        rec["hits"] = [h.cand_index for h in hits]
    except Exception as e:
        rec["ok"] = False
        rec["error"] = f"{type(e).__name__}: {e}"
        rec["traceback"] = traceback.format_exc()[-1200:]
    io.record(f"lower/{name}", rec)


def stage_ext_kernels(io: StageIO):
    """Round-4 extended kernels (ops/pallas_ext.py) on real hardware:
    Mosaic lowering + planted-target proof for the salted and nested
    variants, then sustained worker-path rates (the VERDICT r3 #3
    'done' criterion: >= 10x the XLA mask rate)."""
    from dprf_tpu import get_engine
    from dprf_tpu.generators.mask import MaskGenerator
    from dprf_tpu.runtime.workunit import WorkUnit

    for name, salt in (("md5-ps", b"aXb!"), ("md5-sp", b"na"),
                       ("sha1-ps", b"pepper7"), ("sha256-sp", b"Qx")):
        _prove_planted(io, name, plant=100_003, salt=salt,
                       expected_worker="PallasSaltedMaskWorker")
    for name in ("md5(md5)", "sha1(sha1)", "sha256(sha1)", "mysql41"):
        _prove_planted(io, name, plant=222_222)

    # -- sustained worker-path rates with unmatchable targets (the
    # run_config shape: multi-stride units, one readback per unit)
    def timed_worker(name, w, gen, seconds=15.0):
        unit_len = w.stride * 64
        tested, start = 0, 0
        t0 = time.perf_counter()
        while time.perf_counter() - t0 < seconds:
            length = min(unit_len, gen.keyspace - start)
            if length <= 0:
                start = 0
                continue
            w.process(WorkUnit(-1, start, length))
            tested += length
            start += length
        dt = time.perf_counter() - t0
        return {"metric": f"{name} candidates/sec/chip",
                "value": tested / dt, "unit": "H/s", "engine": name,
                "device": "tpu", "batch": w.stride,
                "unit_strides": 64, "tested": tested,
                "elapsed_s": round(dt, 2)}

    io.status("bench/md5-ps")
    try:
        gen = MaskGenerator("?a?a?a?a?a?a?a?a")
        cpu = get_engine("md5-ps", device="cpu")
        dev = get_engine("md5-ps", device="jax")
        tgt = cpu.parse_target("ff" * 16 + ":saltsalt")
        w = dev.make_mask_worker(gen, [tgt], batch=1 << 22,
                                 hit_capacity=8, oracle=cpu)
        res = timed_worker("md5-ps", w, gen)
        res["worker"] = type(w).__name__
        io.record("bench/md5-ps", res)
    except Exception as e:
        io.record("bench/md5-ps",
                  {"error": f"{type(e).__name__}: {e}",
                   "traceback": traceback.format_exc()[-1200:]})

    io.status("bench/md5(md5)")
    try:
        io.record("bench/md5(md5)",
                  _calibrated_bench(engine="md5(md5)", impl="pallas",
                                    batch=1 << 22))
    except Exception as e:
        io.record("bench/md5(md5)",
                  {"error": f"{type(e).__name__}: {e}",
                   "traceback": traceback.format_exc()[-1200:]})


def stage_rules_kernel(io: StageIO):
    """Round-4 rules-interpreter kernel (ops/pallas_rules.py) on real
    hardware: planted-target proof through the production wordlist
    worker, then the VERDICT criterion measurement -- config 3
    re-measured (run_config auto-selects the kernel on TPU)."""
    import hashlib

    from dprf_tpu import get_engine
    from dprf_tpu.generators.wordlist import WordlistRulesGenerator
    from dprf_tpu.rules.parser import load_rules
    from dprf_tpu.runtime.workunit import WorkUnit

    io.status("prove/md5+best64")
    rec = {}
    try:
        words = [b"alpha", b"bravo", b"s3cret", b"delta", b"echo"] + [
            b"w%05d" % i for i in range(3000)]
        gen = WordlistRulesGenerator(words, load_rules("best64"),
                                     max_len=16)
        cpu = get_engine("md5", device="cpu")
        dev = get_engine("md5", device="jax")
        # plant rule 'd' (duplicate) on "s3cret" -> find via CPU sweep
        from dprf_tpu.rules.cpu import apply_rule
        ri = next(i for i, ops in enumerate(gen.rules)
                  if apply_rule(b"s3cret", ops, 16) == b"s3crets3cret")
        plain = b"s3crets3cret"
        t = cpu.parse_target(hashlib.md5(plain).hexdigest())
        t0 = time.perf_counter()
        w = dev.make_wordlist_worker(gen, [t], batch=1 << 18,
                                     hit_capacity=8, oracle=cpu)
        rec["worker"] = type(w).__name__
        rec["compile_s"] = round(time.perf_counter() - t0, 2)
        hits = w.process(WorkUnit(0, 0, gen.keyspace))
        want = (0, gen.index_of(2, ri))
        rec["ok"] = (rec["worker"] == "PallasWordlistWorker"
                     and want in {(h.target_index, h.cand_index)
                                  for h in hits}
                     and all(cpu.hash_batch([h.plaintext])[0] == t.digest
                             for h in hits))
        rec["hits"] = [h.cand_index for h in hits]
    except Exception as e:
        rec["ok"] = False
        rec["error"] = f"{type(e).__name__}: {e}"
        rec["traceback"] = traceback.format_exc()[-1500:]
    io.record("prove/md5+best64", rec)

    io.status("config3-kernel")
    try:
        from dprf_tpu.bench import run_config
        res = run_config(3, device="jax", **CONFIG_ARGS[3])
        io.record("config3-kernel", res)
    except Exception as e:
        io.record("config3-kernel",
                  {"error": f"{type(e).__name__}: {e}",
                   "traceback": traceback.format_exc()[-1500:]})


STAGES = {
    "kernels": stage_kernels,
    "bench_fast": stage_bench_fast,
    "bench_r4b": stage_bench_r4b,
    "sweep": stage_sweep,
    "ext_kernels": stage_ext_kernels,
    "rules_kernel": stage_rules_kernel,
    **{f"config{n}": _stage_config(n) for n in range(1, 6)},
}


def _stage_case(case_name: str):
    """Any tools/tpu_case.py case as an isolated session stage
    ("case:<name>" in the plan) -- same one-client-per-stage
    protection, results merged into the session document.  Lets a
    session prove a risky shape (e.g. superstep-md5-18-8, the wide
    dispatch) in a disposable child BEFORE the config stages bet
    their deadlines on it."""
    def run(io):
        sys.path.insert(0, os.path.join(REPO, "tools"))
        from tpu_case import run_case
        io.status(case_name)
        io.record(case_name, run_case(case_name))
    run.__name__ = f"stage_case_{case_name}"
    return run


def resolve_stage(stage: str):
    if stage.startswith("case:"):
        return _stage_case(stage[len("case:"):])
    return STAGES[stage]


def child_main(stage: str, out_path: str) -> int:
    io = StageIO(stage, out_path)
    io.status("connect")
    try:
        import jax
        devs = jax.devices()
        io.doc["devices"] = [str(d) for d in devs]
        io.doc["platform"] = devs[0].platform
        if devs[0].platform != "tpu":
            io.finish(ok=False, note="no TPU visible")
            return 1
        resolve_stage(stage)(io)
        io.finish(ok=True)
        return 0
    except Exception as e:
        io.finish(ok=False, error=f"{type(e).__name__}: {e}",
                  traceback=traceback.format_exc()[-2000:])
        return 1


# ------------------------------------------------------------ orchestrator

def _scan_poison(node, flags, path=""):
    """Flag physically impossible rates and backend-fault errors."""
    if isinstance(node, dict):
        v = node.get("value", node.get("hs", 0))
        if isinstance(v, (int, float)) and v > POISON_RATE:
            flags.append(f"{path}: rate {v:.3g} exceeds physical cap")
        err = node.get("error", "")
        if isinstance(err, str) and "TPU device error" in err:
            flags.append(f"{path}: backend fault ({err[:80]})")
        for k, val in node.items():
            _scan_poison(val, flags, f"{path}/{k}")


def write_status(stage, **kw):
    _atomic_write(STATUS, {"stage": stage, "t": time.time(), **kw})


def orchestrate(plan) -> int:
    os.makedirs(WORKDIR, exist_ok=True)
    results = {"round": 4, "plan": plan, "started": time.time(),
               "stages": {}, "poison_flags": []}
    for stage in plan:
        out_path = os.path.join(WORKDIR, f"{stage}.json")
        log_path = os.path.join(WORKDIR, f"{stage}.log")
        try:
            os.unlink(out_path)
        except FileNotFoundError:
            pass
        write_status("spawn", child=stage)
        with open(log_path, "ab") as log:
            proc = subprocess.Popen(
                [sys.executable, os.path.abspath(__file__),
                 "--child", stage, "--out", out_path],
                stdout=log, stderr=log, start_new_session=True,
                cwd=REPO)
        deadline = stage_deadline(stage)
        t0 = time.monotonic()
        doc = None
        while time.monotonic() - t0 < deadline:
            try:
                with open(out_path) as f:
                    doc = json.load(f)
            except (FileNotFoundError, ValueError):
                doc = None
            if doc is not None and doc.get("done"):
                break
            if proc.poll() is not None:
                # child EXITED (crash/OOM -- a clean child always
                # writes done first); its file can no longer change,
                # so don't burn the rest of the deadline.  One last
                # read below picks up whatever it managed to record.
                try:
                    with open(out_path) as f:
                        doc = json.load(f)
                except (FileNotFoundError, ValueError):
                    doc = None
                if doc is None or not doc.get("done"):
                    doc = dict(doc or {"stage": stage, "results": {}},
                               died=True, exit_code=proc.returncode)
                break
            write_status("wait", child=stage,
                         elapsed=round(time.monotonic() - t0),
                         now=(doc or {}).get("now"))
            time.sleep(3)
        if doc is None:
            doc = {"stage": stage, "timeout": True, "results": {}}
        elif not doc.get("done"):
            doc.setdefault("died", False)
            doc["timeout"] = not doc["died"]   # partials are still real
        results["stages"][stage] = doc
        flags = []
        # scan the WHOLE stage doc: a backend fault that escapes a
        # stage's per-case handler lands in doc["error"] via
        # io.finish(ok=False), not under results
        _scan_poison(doc, flags, stage)
        if flags:
            results["poison_flags"].extend(flags)
            write_status("poison_flagged", child=stage, flags=flags)
        _atomic_write(OUT, results)
        # let the child exit and release the single client slot; an
        # abandoned (timed-out) child gets a grace period instead
        time.sleep(15 if doc.get("timeout") else 5)
    results["finished"] = time.time()
    _atomic_write(OUT, results)
    write_status("done", ok=True,
                 poison_flags=results["poison_flags"])
    print(f"TPU session complete: {len(plan)} stages, "
          f"{len(results['poison_flags'])} poison flags -> {OUT}")
    return 0


def main() -> int:
    args = sys.argv[1:]
    if args and args[0] == "--child":
        return child_main(args[1], args[args.index("--out") + 1])
    plan = args if args else DEFAULT_PLAN

    def known(s):
        if s in STAGES:
            return True
        if s.startswith("case:"):
            # validate WITHOUT importing jax (tpu_case's top level is
            # tunnel-free by design): a typo'd kind, wrong parameter
            # count, or non-numeric field must fail fast here, not
            # after a child has taken the tunnel slot
            sys.path.insert(0, os.path.join(REPO, "tools"))
            from tpu_case import case_valid
            return case_valid(s[len("case:"):])
        return False

    unknown = [s for s in plan if not known(s)]
    if unknown:
        sys.stderr.write(f"unknown stages: {unknown}; "
                         f"available: {sorted(STAGES)} or case:<kind>-...\n")
        return 2
    return orchestrate(plan)


if __name__ == "__main__":
    sys.exit(main())
