#!/usr/bin/env python
"""Summarize step-compile cost from telemetry JSONL snapshots.

A fleet that looks stalled is often just compiling (the krb5aes smoke
tier once spent ~9 minutes in XLA compiles); this tool makes that
diagnosable from ARTIFACTS -- the ``*.telemetry.jsonl`` snapshots the
runtime writes next to the session journal -- instead of someone
eyeballing stdout.  It reads the LAST snapshot line (metrics are
cumulative) and reports, per (engine, cache-hit/miss) label pair of
``dprf_compile_seconds``:

    count, p50, p95 (bucket-interpolated), mean, total seconds

plus the persistent-compile-cache hit/miss counters, so "the fleet is
cold-compiling shapes the image should have prewarmed" is one glance.

Usage:
    python tools/compile_report.py SESSION.telemetry.jsonl [...] [--json]

Exit status: 0 with a report, 1 when no snapshot has compile metrics
(still machine-distinguishable from a crash).
"""

from __future__ import annotations

import argparse
import json
import math
import os
import sys


def _load_last_snapshot(path: str):
    """Last parseable snapshot line of a JSONL file (None when the
    file is missing/empty/torn -- same tolerance as the runtime's
    loader, without importing the package)."""
    last = None
    try:
        with open(path, encoding="utf-8") as fh:
            for line in fh:
                line = line.strip()
                if not line:
                    continue
                try:
                    doc = json.loads(line)
                except json.JSONDecodeError:
                    continue
                if isinstance(doc, dict) and "metrics" in doc:
                    last = doc
    except OSError:
        return None
    return last


def _percentile(buckets: dict, total: int, q: float) -> float:
    """Bucket-interpolated percentile.  `buckets` maps upper-bound
    strings (plus "+Inf") to per-bucket counts; observations inside a
    bucket are assumed uniform.  The +Inf bucket reports the largest
    finite bound (a floor -- honest, since the true value is off the
    histogram's scale)."""
    bounds = []
    for k, c in buckets.items():
        ub = math.inf if k == "+Inf" else float(k)
        bounds.append((ub, int(c)))
    bounds.sort(key=lambda t: t[0])
    want = q * total
    cum = 0.0
    lo = 0.0
    largest_finite = max((b for b, _ in bounds if b != math.inf),
                        default=0.0)
    for ub, count in bounds:
        if count <= 0:
            lo = ub if ub != math.inf else lo
            continue
        if cum + count >= want:
            if ub == math.inf:
                return largest_finite
            frac = (want - cum) / count
            return lo + frac * (ub - lo)
        cum += count
        lo = ub
    return largest_finite


def _metric_values(snapshot: dict, name: str) -> list:
    m = snapshot.get("metrics", {}).get(name)
    if not isinstance(m, dict):
        return []
    vals = m.get("values")
    return vals if isinstance(vals, list) else []


def summarize(snapshot: dict) -> dict:
    """The report document for one snapshot line."""
    rows = []
    for v in _metric_values(snapshot, "dprf_compile_seconds"):
        count = int(v.get("count", 0))
        if count <= 0:
            continue
        labels = v.get("labels", {})
        buckets = v.get("buckets", {})
        total_s = float(v.get("sum", 0.0))
        rows.append({
            "engine": labels.get("engine", "?"),
            # pre-ISSUE-3 snapshots have no cache label; report "n/a"
            # rather than guessing
            "cache": labels.get("cache", "n/a"),
            "count": count,
            "p50_s": round(_percentile(buckets, count, 0.50), 3),
            "p95_s": round(_percentile(buckets, count, 0.95), 3),
            "mean_s": round(total_s / count, 3),
            "total_s": round(total_s, 3),
        })
    rows.sort(key=lambda r: (-r["total_s"], r["engine"], r["cache"]))
    counters = {"hits": 0, "misses": 0}
    for name, key in (("dprf_compile_cache_hits_total", "hits"),
                      ("dprf_compile_cache_misses_total", "misses")):
        for v in _metric_values(snapshot, name):
            counters[key] += int(v.get("value", 0))
    return {"ts": snapshot.get("ts"),
            "elapsed_s": snapshot.get("elapsed_s"),
            "compiles": rows,
            "cache_hits": counters["hits"],
            "cache_misses": counters["misses"]}


def render(report: dict, source: str) -> str:
    rows = [("engine", "cache", "count", "p50_s", "p95_s", "mean_s",
             "total_s")]
    for r in report["compiles"]:
        rows.append((r["engine"], r["cache"], str(r["count"]),
                     f"{r['p50_s']:.2f}", f"{r['p95_s']:.2f}",
                     f"{r['mean_s']:.2f}", f"{r['total_s']:.2f}"))
    widths = [max(len(row[i]) for row in rows)
              for i in range(len(rows[0]))]
    lines = [f"compile report: {source} "
             f"(snapshot at elapsed {report.get('elapsed_s')}s)"]
    lines += ["  ".join(c.ljust(w) for c, w in zip(row, widths))
              for row in rows]
    h, m = report["cache_hits"], report["cache_misses"]
    ratio = f"{100.0 * h / (h + m):.0f}%" if h + m else "n/a"
    lines.append(f"persistent compile cache: {h} hits / {m} misses "
                 f"(hit ratio {ratio})")
    return "\n".join(lines)


def main(argv=None) -> int:
    p = argparse.ArgumentParser(
        description="summarize dprf_compile_seconds from telemetry "
        "JSONL snapshots")
    p.add_argument("snapshots", nargs="+",
                   help="*.telemetry.jsonl files (session journal "
                   "siblings)")
    p.add_argument("--json", action="store_true",
                   help="machine output: one JSON document per file")
    args = p.parse_args(argv)

    any_data = False
    out_docs = []
    for path in args.snapshots:
        snap = _load_last_snapshot(path)
        if snap is None:
            print(f"compile report: {path}: no parseable snapshots",
                  file=sys.stderr)
            out_docs.append({"source": path, "error": "no snapshots"})
            continue
        report = summarize(snap)
        if report["compiles"] or report["cache_hits"] \
                or report["cache_misses"]:
            any_data = True
        out_docs.append({"source": os.path.basename(path), **report})
        if not args.json:
            print(render(report, path))
    if args.json:
        print(json.dumps(out_docs if len(out_docs) > 1 else out_docs[0]))
    return 0 if any_data else 1


if __name__ == "__main__":
    sys.exit(main())
