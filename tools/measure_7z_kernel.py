"""Direct hardware measurement of the 7z KDF Pallas kernel.

Times ops/pallas_7z.make_7z_kdf_pallas_fn standalone (no worker, no
oracle) at the production cycles=19 stream, one (SUB, batch) point per
invocation so a deadline trip can't take other points down with it.

Usage: python tools/measure_7z_kernel.py <sub> <logB> [cycles]
Appends one JSON line to TPU_CASES_OUT (default /tmp/tpu_cases.jsonl).
"""

import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

OUT = os.environ.get("TPU_CASES_OUT", "/tmp/tpu_cases.jsonl")


def main():
    sub, logB = int(sys.argv[1]), int(sys.argv[2])
    cycles = int(sys.argv[3]) if len(sys.argv) > 3 else 19
    doc = {"case": f"7zkdf-{sub}-{logB}-{cycles}", "t": time.time()}
    try:
        import jax.numpy as jnp
        from dprf_tpu.generators.mask import MaskGenerator
        from dprf_tpu.ops.pallas_7z import make_7z_kdf_pallas_fn
        from dprf_tpu.utils.sync import hard_sync

        B = 1 << logB
        gen = MaskGenerator("?a?a?a?a?a?a?a?a")
        kdf = make_7z_kdf_pallas_fn(gen, B, b"Qx", cycles, sub=sub)
        base = jnp.asarray(gen.digits(0), jnp.int32)
        t0 = time.perf_counter()
        hard_sync(kdf(base))
        doc["compile_s"] = round(time.perf_counter() - t0, 1)
        k, t0 = 0, time.perf_counter()
        while True:
            hard_sync(kdf(base))
            k += 1
            if time.perf_counter() - t0 > 30.0 or k >= 16:
                break
        dt = time.perf_counter() - t0
        doc.update(ok=True, hs=k * B / dt, batch=B, sub=sub,
                   cycles=cycles, dispatches=k,
                   dispatch_s=round(dt / k, 2))
    except Exception as e:  # noqa: BLE001 -- report, don't crash
        import traceback
        doc.update(ok=False, error=f"{type(e).__name__}: {e}",
                   traceback=traceback.format_exc()[-800:])
    with open(OUT, "a") as f:
        f.write(json.dumps(doc) + "\n")
    print(json.dumps(doc)[:300])
    return 0 if doc.get("ok") else 1


if __name__ == "__main__":
    sys.exit(main())
