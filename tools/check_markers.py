#!/usr/bin/env python
"""Tier-marker hygiene for the test suite (run at the top of tier-1).

The smoke tier promises <5 minutes (pytest.ini); its wall time is
runtime-guarded by tests/conftest.py.  What the runtime guard cannot
catch is a NEW test that compiles device pipelines and rides into a
tier nobody budgeted, because its author never declared a tier at all.

Rule enforced here: any test module that uses Pallas kernels or JAX
device engines -- statically imports ``dprf_tpu.ops.pallas_*`` /
``dprf_tpu.engines.device*`` anywhere (module or function level), or
requests ``device="jax"`` / ``device='jax'`` in source -- must declare
an explicit tier decision: at least one ``pytest.mark.smoke`` (fast;
the conftest wall-time guard holds it to the budget),
``pytest.mark.compileheavy`` (full suite only, out of the smoke tier),
or ``pytest.mark.slow`` (out of the tier-1 gate) marker.

Exit status 1 lists the violating files; 0 means clean.
"""

from __future__ import annotations

import ast
import os
import re
import sys

HEAVY_PREFIXES = ("dprf_tpu.ops.pallas_", "dprf_tpu.engines.device")
TIER_MARK_RE = re.compile(r"pytest\.mark\.(smoke|compileheavy|slow)\b")
DEVICE_USE_RE = re.compile(r"""device\s*=\s*["']jax["']""")


def _imported_modules(tree: ast.AST):
    """Every dotted module name the file imports, at any nesting depth
    (tests routinely import device engines inside test functions)."""
    for node in ast.walk(tree):
        if isinstance(node, ast.Import):
            for alias in node.names:
                yield alias.name
        elif isinstance(node, ast.ImportFrom) and node.module:
            yield node.module
            for alias in node.names:
                # `from dprf_tpu.ops import pallas_mask` names the
                # heavy module in the alias, not in node.module
                yield f"{node.module}.{alias.name}"


def check_file(path: str):
    """None if clean, else a one-line violation message."""
    with open(path, encoding="utf-8") as fh:
        src = fh.read()
    try:
        tree = ast.parse(src, filename=path)
    except SyntaxError as e:
        return f"{path}: does not parse ({e})"
    heavy = (any(m.startswith(HEAVY_PREFIXES)
                 for m in _imported_modules(tree))
             or DEVICE_USE_RE.search(src) is not None)
    if not heavy:
        return None
    if TIER_MARK_RE.search(src):
        return None
    return (f"{path}: uses Pallas/device engines but declares no tier "
            "marker -- add pytest.mark.smoke (fast, budget-checked), "
            "compileheavy, or slow")


def main(argv=None) -> int:
    argv = sys.argv[1:] if argv is None else argv
    if argv:
        test_dir = argv[0]
    else:
        test_dir = os.path.join(
            os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
            "tests")
    violations = []
    for name in sorted(os.listdir(test_dir)):
        if not (name.startswith("test_") and name.endswith(".py")):
            continue
        msg = check_file(os.path.join(test_dir, name))
        if msg:
            violations.append(msg)
    if violations:
        print("check_markers: tier-marker violations:\n  "
              + "\n  ".join(violations))
        return 1
    print(f"check_markers: OK ({test_dir})")
    return 0


if __name__ == "__main__":
    sys.exit(main())
