#!/usr/bin/env python
"""Thin shim over `dprf check --only markers` (the tier-marker lint
moved into the plugin framework at dprf_tpu/analysis/markers.py; this
entry point stays so existing workflows keep working).

Exit status 1 lists the violating files; 0 means clean.
"""

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

from dprf_tpu import analysis  # noqa: E402

if __name__ == "__main__":
    sys.exit(analysis.shim_main("markers", "tests_dir"))
