#!/usr/bin/env python
"""Metric/span declaration hygiene for dprf_tpu (run at the top of
every tier, like check_markers).

The PR 3 bug this makes impossible: ``dprf_compile_seconds`` was
declared with ``("engine",)`` labels in two call sites and with
``("engine", "cache")`` in a third -- the registry's get-or-create
semantics turn a second declaration site into either silent drift or a
runtime ValueError, depending on which import runs first.  Single
declaration sites (telemetry.declare_job_metrics,
compilecache.compile_histogram) are the fix; this lint enforces the
policy statically:

  1. every ``dprf_*`` metric name passed as a literal to
     ``.counter(`` / ``.gauge(`` / ``.histogram(`` appears at EXACTLY
     ONE call site across the package (call the one site's helper
     instead of re-declaring);
  2. every span-name literal passed to a ``.record("...")`` call is a
     member of ``telemetry/trace.py``'s ``SPAN_NAMES`` tuple -- the
     single span-name declaration site -- and that tuple holds no
     duplicates.

Exit status 1 lists violations; 0 means clean.
"""

from __future__ import annotations

import ast
import os
import sys

METRIC_METHODS = {"counter", "gauge", "histogram"}
TRACE_REL = os.path.join("telemetry", "trace.py")


def _literal(node) -> str | None:
    if isinstance(node, ast.Constant) and isinstance(node.value, str):
        return node.value
    return None


def scan_file(path: str):
    """(metric declarations, span-name uses) as [(name, lineno), ...];
    a parse failure returns an error string instead."""
    with open(path, encoding="utf-8") as fh:
        src = fh.read()
    try:
        tree = ast.parse(src, filename=path)
    except SyntaxError as e:
        return f"{path}: does not parse ({e})"
    decls, span_uses = [], []
    for node in ast.walk(tree):
        if not (isinstance(node, ast.Call)
                and isinstance(node.func, ast.Attribute)):
            continue
        first = _literal(node.args[0]) if node.args else None
        if (node.func.attr in METRIC_METHODS and first
                and first.startswith("dprf_")):
            decls.append((first, node.lineno))
        elif node.func.attr == "record" and first is not None:
            span_uses.append((first, node.lineno))
    return decls, span_uses


def declared_span_names(trace_py: str):
    """The SPAN_NAMES tuple from telemetry/trace.py, or None when the
    file/assignment is missing."""
    if not os.path.exists(trace_py):
        return None
    with open(trace_py, encoding="utf-8") as fh:
        try:
            tree = ast.parse(fh.read(), filename=trace_py)
        except SyntaxError:
            return None
    for node in ast.walk(tree):
        if not isinstance(node, ast.Assign):
            continue
        if not any(isinstance(t, ast.Name) and t.id == "SPAN_NAMES"
                   for t in node.targets):
            continue
        if isinstance(node.value, (ast.Tuple, ast.List)):
            names = [_literal(e) for e in node.value.elts]
            if all(n is not None for n in names):
                return names
    return None


def main(argv=None) -> int:
    argv = sys.argv[1:] if argv is None else argv
    if argv:
        pkg_dir = argv[0]
    else:
        pkg_dir = os.path.join(
            os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
            "dprf_tpu")
    violations = []
    decl_sites: dict = {}    # metric name -> [site, ...]
    span_sites = []          # (name, site)
    for root, dirs, files in os.walk(pkg_dir):
        dirs[:] = [d for d in dirs if d != "__pycache__"]
        for name in sorted(files):
            if not name.endswith(".py"):
                continue
            path = os.path.join(root, name)
            res = scan_file(path)
            if isinstance(res, str):
                violations.append(res)
                continue
            decls, span_uses = res
            rel = os.path.relpath(path, pkg_dir)
            for metric, lineno in decls:
                decl_sites.setdefault(metric, []).append(f"{rel}:{lineno}")
            for span, lineno in span_uses:
                span_sites.append((span, f"{rel}:{lineno}"))

    for metric, sites in sorted(decl_sites.items()):
        if len(sites) > 1:
            violations.append(
                f"metric {metric!r} declared at {len(sites)} sites "
                f"({', '.join(sites)}) -- declare once and share the "
                "helper (telemetry.declare_job_metrics pattern)")

    span_names = declared_span_names(os.path.join(pkg_dir, TRACE_REL))
    if span_names is None:
        if span_sites:
            violations.append(
                f"{TRACE_REL}: SPAN_NAMES tuple not found but "
                f"{len(span_sites)} .record(...) call sites exist")
    else:
        dupes = {n for n in span_names if span_names.count(n) > 1}
        if dupes:
            violations.append(
                f"{TRACE_REL}: duplicate SPAN_NAMES entries: "
                f"{sorted(dupes)}")
        allowed = set(span_names)
        for span, site in span_sites:
            if span not in allowed:
                violations.append(
                    f"{site}: span {span!r} not declared in "
                    f"{TRACE_REL} SPAN_NAMES")

    if violations:
        print("check_metrics: declaration violations:\n  "
              + "\n  ".join(violations))
        return 1
    print(f"check_metrics: OK ({len(decl_sites)} metrics, "
          f"{len(span_sites)} span sites, {pkg_dir})")
    return 0


if __name__ == "__main__":
    sys.exit(main())
