#!/usr/bin/env python
"""Thin shim over `dprf check --only worker-contract` (the worker
pipelining-contract lint moved into the plugin framework at
dprf_tpu/analysis/worker_contract.py; this entry point stays so
existing workflows keep working).

Exit status 1 lists the violations; 0 means clean.
"""

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

from dprf_tpu import analysis  # noqa: E402

if __name__ == "__main__":
    sys.exit(analysis.shim_main("worker-contract", "package_dir"))
