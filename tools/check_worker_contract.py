#!/usr/bin/env python
"""Worker pipelining-contract hygiene (run at the top of every tier,
like check_markers / check_metrics).

``runtime/worker.py``'s ``submit_or_process`` pipelines a worker only
when its ``process`` carries ``_submit_based = True``; everything else
runs serially.  Before this lint the contract was convention-only: a
worker class overriding ``process()`` without re-marking silently
degraded pipelining (the pre-ISSUE-5 ShardedWordlistWorker did exactly
that), and a class that grew a ``submit()`` but forgot the marker
never pipelined at all.

Rule enforced here: every class in the package that defines a
``process(self, unit)`` method must declare its pipelining stance in
its own body, exactly one of:

  1. ``process._submit_based = True`` -- and then the class must also
     define ``submit`` itself (inheriting one under an overridden
     ``process`` is the bug the marker exists to prevent: the
     inherited submit would bypass the override's sweep logic);
  2. ``process._serial_only = True`` -- an explicit "this worker's
     process does its own internal overlap / has no device stream;
     do not pipeline it".

Exit status 1 lists violations; 0 means clean.
"""

from __future__ import annotations

import ast
import os
import sys


def _marker_assignments(cls: ast.ClassDef):
    """The ``process.<attr> = True`` statements in a class body."""
    for node in cls.body:
        if not (isinstance(node, ast.Assign) and len(node.targets) == 1):
            continue
        t = node.targets[0]
        if (isinstance(t, ast.Attribute) and isinstance(t.value, ast.Name)
                and t.value.id == "process"
                and isinstance(node.value, ast.Constant)
                and node.value.value is True):
            yield t.attr


def check_file(path: str) -> list:
    with open(path, encoding="utf-8") as fh:
        src = fh.read()
    try:
        tree = ast.parse(src, filename=path)
    except SyntaxError as e:
        return [f"{path}: does not parse ({e})"]
    out = []
    for node in ast.walk(tree):
        if not isinstance(node, ast.ClassDef):
            continue
        defs = {n.name for n in node.body
                if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef))}
        if "process" not in defs:
            continue
        markers = set(_marker_assignments(node))
        where = f"{path}:{node.lineno}: class {node.name}"
        if "_submit_based" in markers and "_serial_only" in markers:
            out.append(f"{where} marks process BOTH _submit_based and "
                       "_serial_only -- pick one")
        elif "_submit_based" in markers:
            if "submit" not in defs:
                out.append(
                    f"{where} marks process._submit_based but defines "
                    "no submit() of its own -- an inherited submit "
                    "bypasses the overridden process; define submit "
                    "or mark process._serial_only")
        elif "_serial_only" not in markers:
            out.append(
                f"{where} overrides process() without declaring its "
                "pipelining stance -- set `process._submit_based = "
                "True` (and define submit) or `process._serial_only "
                "= True` after the def; an unmarked override silently "
                "degrades submit_or_process to the serial path")
    return out


def main(argv=None) -> int:
    argv = sys.argv[1:] if argv is None else argv
    if argv:
        pkg_dir = argv[0]
    else:
        pkg_dir = os.path.join(
            os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
            "dprf_tpu")
    violations = []
    n_files = 0
    for root, dirs, files in os.walk(pkg_dir):
        dirs[:] = [d for d in dirs if d != "__pycache__"]
        for name in sorted(files):
            if not name.endswith(".py"):
                continue
            n_files += 1
            violations.extend(check_file(os.path.join(root, name)))
    if violations:
        print("check_worker_contract: pipelining-contract violations:"
              "\n  " + "\n  ".join(violations))
        return 1
    print(f"check_worker_contract: OK ({n_files} files, {pkg_dir})")
    return 0


if __name__ == "__main__":
    sys.exit(main())
