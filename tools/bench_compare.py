#!/usr/bin/env python
"""Bench regression sentinel CLI (ISSUE 9).

Gate a bench measurement against the committed BENCH_r*.json
trajectory: the baseline is the median of the last K records measured
on the SAME device backend, with a noise tolerance derived from their
observed run-to-run spread (never below the 10% floor).  Exit 1 on
regression, 0 on pass/no-baseline, 2 on unusable input.

Usage::

    python tools/bench_compare.py --current result.json   # gate a file
    python tools/bench_compare.py --dry                   # newest committed
                                                          # record vs the
                                                          # window before it
        [--dir REPO] [--window K] [--quiet]

The comparison logic lives in dprf_tpu/perfreport/compare.py, shared
with ``dprf bench --gate``.
"""

from __future__ import annotations

import argparse
import json
import os
import sys

sys.path.insert(0, os.path.dirname(
    os.path.dirname(os.path.abspath(__file__))))


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        description="gate a bench result against the committed "
        "BENCH_r*.json baseline window")
    ap.add_argument("--current", default=None, metavar="FILE",
                    help="bench result JSON to gate (a dprf bench "
                    "stdout line or a driver BENCH record)")
    ap.add_argument("--dry", action="store_true",
                    help="gate the newest committed record against "
                    "the window before it (no fresh measurement)")
    ap.add_argument("--dir", default=None, metavar="REPO",
                    help="directory holding BENCH_r*.json (default: "
                    "the repo root this tree is installed in)")
    ap.add_argument("--scaling", action="store_true",
                    help="gate against the committed SCALING_r*.json "
                    "trajectory (multichip efficiency records) instead "
                    "of the BENCH throughput records")
    ap.add_argument("--targets", action="store_true",
                    help="gate against the committed TARGETS_r*.json "
                    "trajectory (probe-table target-set-size sweep "
                    "records) instead of the BENCH throughput records")
    ap.add_argument("--ttfh", action="store_true",
                    help="gate against the committed TTFH_r*.json "
                    "trajectory (time-to-first-hit speedup of rank-"
                    "ordered over linear dispatch) instead of the "
                    "BENCH throughput records")
    ap.add_argument("--window", type=int, default=None, metavar="K")
    ap.add_argument("--quiet", "-q", action="store_true")
    args = ap.parse_args(argv)

    from dprf_tpu.perfreport import compare

    repo = args.dir or compare.repo_root()
    window = args.window or compare.DEFAULT_WINDOW
    if args.ttfh:
        pattern = compare.TTFH_PATTERN
    elif args.targets:
        pattern = compare.TARGETS_PATTERN
    elif args.scaling:
        pattern = compare.SCALING_PATTERN
    else:
        pattern = "BENCH_r*.json"
    if args.dry:
        verdict = compare.gate_dry(repo, window=window, pattern=pattern)
    elif args.current:
        try:
            with open(args.current, encoding="utf-8") as fh:
                doc = json.load(fh)
        except (OSError, json.JSONDecodeError) as e:
            print(f"bench_compare: unreadable --current: {e}",
                  file=sys.stderr)
            return 2
        if isinstance(doc, dict) and isinstance(doc.get("tail"), str):
            doc = compare._result_from_tail(doc["tail"]) or {}
        verdict = compare.gate_repo(doc, repo, window=window,
                                    pattern=pattern)
    else:
        print("bench_compare: pass --current FILE or --dry",
              file=sys.stderr)
        return 2
    print(json.dumps(verdict, sort_keys=True))
    if not args.quiet and verdict["verdict"] == "regression":
        print(f"bench_compare: REGRESSION — current/median ratio "
              f"{verdict['ratio']} below tolerance "
              f"{verdict['tolerance']} (window of "
              f"{verdict['window']})", file=sys.stderr)
    return 1 if verdict["verdict"] == "regression" else 0


if __name__ == "__main__":
    sys.exit(main())
