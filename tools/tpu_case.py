"""Run ONE risky TPU bench case in its own client process.

A device fault (or a dispatch that trips the server-side deadline)
poisons the whole client backend, so the slow/memory-hard cases are
isolated: one case per process, clean exit either way, results
appended to TPU_CASES_OUT as one JSON line per case.

Usage: python tools/tpu_case.py <case>
Cases: scrypt-<N>-<r>-<p>-<B> | bcrypt-<cost>-<B> | pmkid-<B>
     | bcryptchunk-<cost>-<B>   (deadline-bounded chunked cost loop;
                                 the only safe shape for cost >= 10)
     | descrypt-<B>             (bitslice crypt(3): 25 chained DES)
     | pallaseks-<cost>-<B>     (Pallas EksBlowfish advance kernel:
                                 on-chip equivalence vs the XLA form,
                                 then a chunked timed run)
"""

import json
import os
import sys
import time
import traceback

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

OUT = os.environ.get("TPU_CASES_OUT", "/tmp/tpu_cases.jsonl")

#: case-name kinds run_case understands (first dash-field) -> number
#: of dash-parameters after the kind.  Kept as data so orchestrators
#: (tools/tpu_session.py) can validate a whole plan WITHOUT importing
#: jax / touching the tunnel.
KINDS = {"scrypt": 4, "bcrypt": 2, "bcryptchunk": 2, "pallaseks": 2,
         "descrypt": 1, "pmkid": 1, "scanprobe": 2, "superstep": 3,
         "krb5": 1, "krb5cfg": 3, "pdf": 2, "sevenzip": 2,
         "krb5aes": 2}


def case_valid(name: str) -> bool:
    """Cheap, tunnel-free well-formedness check for a case name:
    known kind, right parameter count, numeric fields numeric."""
    parts = name.split("-")
    kind = parts[0]
    if kind not in KINDS or len(parts) - 1 != KINDS[kind]:
        return False
    # every parameter is an int except scanprobe's variant and
    # superstep's engine name (parts[1] for both)
    num_from = 2 if kind in ("scanprobe", "superstep") else 1
    return all(p.isdigit() for p in parts[num_from:])


def emit(doc):
    with open(OUT, "a") as f:
        f.write(json.dumps(doc) + "\n")


def timed_sweep(worker, WorkUnit, seconds: float):
    """Timed production-worker sweep crediting whole strides.

    The worker may round its batch up to the Pallas tile (stride >
    requested batch), so credit `worker.stride` per unit or the rate
    under-reports by stride/batch (r4 session11 misread 4x low); burn
    one unit first so the sweep-step compile stays outside the timed
    window.  Returns (hs, tested, elapsed, stride)."""
    stride = worker.stride
    worker.process(WorkUnit(-1, 0, stride))
    tested, start = 0, stride
    t0 = time.perf_counter()
    while time.perf_counter() - t0 < seconds:
        worker.process(WorkUnit(-1, start, stride))
        tested += stride
        start += stride
    dt = time.perf_counter() - t0
    return tested / dt, tested, dt, stride


def run_case(name: str) -> dict:
    import numpy as np
    import jax
    import jax.numpy as jnp

    from dprf_tpu.generators.mask import MaskGenerator

    parts = name.split("-")
    kind = parts[0]
    gen = MaskGenerator("?l?l?l?l?l?l?l?l")
    base = jnp.asarray(gen.digits(0), jnp.int32)

    if kind == "scrypt":
        n, r, p, B = (int(x) for x in parts[1:])
        from dprf_tpu.ops.hmac import pack_raw_varlen
        from dprf_tpu.ops.scrypt import scrypt_dk
        flat = gen.flat_charsets

        @jax.jit
        def run(b):
            cand = gen.decode_batch(b, flat, B)
            kw = pack_raw_varlen(cand, jnp.full((B,), 8, jnp.int32),
                                 True)
            dk = scrypt_dk(kw, jnp.zeros((51,), jnp.uint8),
                           jnp.int32(8), n, r, p)
            return dk.sum()
    elif kind == "bcrypt":
        cost, B = (int(x) for x in parts[1:])
        from dprf_tpu.engines.device.bcrypt import make_bcrypt_mask_step
        g6 = MaskGenerator("?l?l?l?l?l?l")
        base = jnp.asarray(g6.digits(0), jnp.int32)
        step = make_bcrypt_mask_step(g6, B)
        sw = jnp.asarray(np.frombuffer(bytes(range(16)), ">u4")
                         .astype(np.uint32))
        tgt = jnp.full((6,), 0xFFFFFFFF, jnp.uint32)

        @jax.jit
        def run(b):
            return step(b, jnp.int32(B), sw, jnp.int32(1 << cost),
                        tgt)[0]
    elif kind == "bcryptchunk":
        # One full batch through the deadline-bounded chunked path
        # (begin -> ChunkedEks.run -> finish): no single dispatch holds
        # the whole 2**cost chain, so cost 12 cannot trip the tunnel's
        # per-dispatch execution deadline the way session3's one-shot
        # step did.
        cost, B = (int(x) for x in parts[1:])
        from dprf_tpu.engines.device.bcrypt import (
            ChunkedEks, make_bcrypt_mask_chunk_fns)
        g6 = MaskGenerator("?l?l?l?l?l?l")
        base6 = jnp.asarray(g6.digits(0), jnp.int32)
        begin, finish = make_bcrypt_mask_chunk_fns(g6, B)
        sw = jnp.asarray(np.frombuffer(bytes(range(16)), ">u4")
                         .astype(np.uint32))
        from dprf_tpu.ops import blowfish as bf_ops
        salt18 = bf_ops.salt18_words(sw)
        tgt = jnp.full((6,), 0xFFFFFFFF, jnp.uint32)
        chunker = ChunkedEks()
        marks = [time.perf_counter()]
        t0 = marks[0]
        kw, P, S = begin(base6, sw)
        P, S = chunker.run(P, S, kw, salt18, 1 << cost,
                           on_chunk=lambda d, t: marks.append(
                               time.perf_counter()))
        count = int(finish(P, S, jnp.int32(B), tgt)[0])
        dt = time.perf_counter() - t0
        steps = [marks[i + 1] - marks[i] for i in range(len(marks) - 1)]
        return {"case": name, "ok": True, "hs": B / dt, "batch": B,
                "rounds": 1 << cost, "total_s": round(dt, 1),
                "n_dispatches": len(steps) + 2,
                "max_dispatch_s": round(max(steps), 1),
                "false_hits": count}
    elif kind == "pallaseks":
        # Pallas EksBlowfish advance (ops/pallas_bcrypt.py): first an
        # on-chip bit-equivalence check vs the XLA eks_rounds at 2
        # rounds, then the full 2**cost chain through ChunkedEks with
        # the kernel as the advance fn.
        cost, B = (int(x) for x in parts[1:])
        from dprf_tpu.engines.device.bcrypt import ChunkedEks
        from dprf_tpu.ops import blowfish as bf_ops
        from dprf_tpu.ops.pallas_bcrypt import make_pallas_eks_advance
        from dprf_tpu.utils.sync import hard_sync

        rng = np.random.RandomState(7)
        cand = rng.randint(97, 123, (B, 6), dtype=np.uint8)
        lens = np.full((B,), 6, np.int32)
        kw = bf_ops.key_words_from_candidates(jnp.asarray(cand),
                                              jnp.asarray(lens))
        sw = jnp.asarray(np.frombuffer(bytes(range(16)), ">u4")
                         .astype(np.uint32))
        s18 = bf_ops.salt18_words(sw)
        P0, S0 = bf_ops.eks_setup_begin(kw, sw)
        hard_sync(S0)
        adv = make_pallas_eks_advance(B)
        t0 = time.perf_counter()
        Pk, Sk = adv(P0, S0, kw, s18, jnp.int32(2))
        hard_sync(Sk)
        compile_s = time.perf_counter() - t0
        Pr, Sr = bf_ops.eks_rounds(P0, S0, kw, s18, jnp.int32(2))
        equal = (np.array_equal(np.asarray(Pk), np.asarray(Pr))
                 and np.array_equal(np.asarray(Sk), np.asarray(Sr)))
        # timed: full 2**cost chain, deadline-chunked via the kernel
        chunker = ChunkedEks(advance=adv)
        t0 = time.perf_counter()
        P, S = bf_ops.eks_setup_begin(kw, sw)
        P, S = chunker.run(P, S, kw, s18, 1 << cost)
        dw = bf_ops.bcrypt_digest_words(P, S)
        hard_sync(dw)
        dt = time.perf_counter() - t0
        return {"case": name, "ok": equal, "equal_2rounds": equal,
                "hs": B / dt, "batch": B, "rounds": 1 << cost,
                "total_s": round(dt, 1), "compile_s": round(compile_s, 1),
                "per_round_s": chunker._per_round}
    elif kind == "descrypt":
        B = int(parts[1])
        from dprf_tpu.engines.device.descrypt import (
            make_descrypt_mask_step)
        from dprf_tpu.engines.base import Target
        g6 = MaskGenerator("?l?l?l?l?l?l")
        base = jnp.asarray(g6.digits(0), jnp.int32)
        # plant the 5th candidate of the keyspace under salt "ab" (12)
        from dprf_tpu.ops.des import des_crypt25, descrypt_key8
        plain = g6.candidate(4)
        tgt = Target(raw="x", digest=des_crypt25(descrypt_key8(plain),
                                                 12),
                     params={"salt": 12, "salt_text": "ab"})
        step = make_descrypt_mask_step(g6, [tgt], B)

        @jax.jit
        def run(b):
            return step(b, jnp.int32(B))[0]
    elif kind == "pmkid":
        B = int(parts[1])
        from dprf_tpu import get_engine
        from dprf_tpu.engines.device.pmkid import make_pmkid_crack_step
        eng = get_engine("wpa2-pmkid", device="jax")
        tgt = eng.parse_target("%s*0a1b2c3d4e5f*a0b1c2d3e4f5*%s"
                               % ("ff" * 16, b"benchnet".hex()))
        step = make_pmkid_crack_step(eng, gen, [tgt], B)

        @jax.jit
        def run(b):
            return step(b, jnp.int32(B))[0]
    elif kind == "krb5":
        # krb5-<logB>: the Kerberos etype-23 DER-prefilter worker
        # (NTLM -> HMAC-MD5 chain -> RC4 KSA, a fori_loop of per-lane
        # gathers/scatters -- the shape whose TPU behavior is the open
        # question).  Planted-crack proof on a small keyspace through
        # the PRODUCTION worker, then a timed sweep; returns directly.
        import hmac as hmac_mod

        from dprf_tpu import get_engine
        from dprf_tpu.engines.cpu.krb5 import TGS_MSG_TYPE, rc4
        from dprf_tpu.engines.cpu.md4 import md4
        from dprf_tpu.runtime.workunit import WorkUnit
        B = 1 << int(parts[1])
        eng = get_engine("krb5tgs", device="jax")
        cpu = get_engine("krb5tgs", device="cpu")

        def line(pw: bytes, fill: int) -> str:
            body = bytes((fill + i) % 256 for i in range(512))
            inner = bytes([0x30, 0x82, 0x02, 0x00]) + body
            plain = bytes(8) + bytes([0x63, 0x82, 0x02, 0x04]) + inner
            nt = md4(pw.decode("latin-1").encode("utf-16-le"))
            k1 = hmac_mod.new(nt, TGS_MSG_TYPE.to_bytes(4, "little"),
                              "md5").digest()
            chk = hmac_mod.new(k1, plain, "md5").digest()
            ed = rc4(hmac_mod.new(k1, chk, "md5").digest(), plain)
            return f"$krb5tgs$23${chk.hex()}${ed.hex()}"

        g5 = MaskGenerator("?l?l?l?l?l")
        plant = 777_001
        t0 = time.perf_counter()
        w = eng.make_mask_worker(g5, [cpu.parse_target(
            line(g5.candidate(plant), 1))], batch=B, hit_capacity=8,
            oracle=cpu)
        hits = w.process(WorkUnit(-1, plant - plant % B, B))
        compile_s = time.perf_counter() - t0
        ok = [(h.target_index, h.cand_index) for h in hits] == [(0, plant)]

        # timed sweep: a target whose edata2 bytes [8,12) cannot
        # decrypt to the expected DER header for (almost) any
        # candidate; stray 2^-32 maybes only cost an oracle check
        g8 = MaskGenerator("?a?a?a?a?a?a?a?a")
        sweep = eng.make_mask_worker(g8, [cpu.parse_target(
            line(b"absent!", 7))], batch=B, hit_capacity=64,
            oracle=cpu)
        hs, tested, dt, stride = timed_sweep(sweep, WorkUnit, 15.0)
        return {"case": name, "ok": ok, "batch": stride,
                "compile_s": round(compile_s, 1),
                "hs": hs, "tested": tested,
                "elapsed_s": round(dt, 2),
                "hits": [h.cand_index for h in hits]}
    elif kind in ("pdf", "sevenzip"):
        # pdf-<rev>-<logB> / sevenzip-<cycles>-<logB>: planted crack
        # on a small keyspace through the PRODUCTION worker, then a
        # timed sweep with an absent password.  Both engines build
        # self-consistent targets by running the spec forward (the
        # same constructors the hermetic tests use).
        import sys as _sys
        _sys.path.insert(0, os.path.join(
            os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
            "tests"))
        from dprf_tpu import get_engine
        from dprf_tpu.runtime.workunit import WorkUnit
        a, logB = int(parts[1]), int(parts[2])
        B = 1 << logB
        if kind == "pdf":
            from test_pdf import _line as mk
            ename = "pdf"
            line = lambda pw: mk(pw, a)
        else:
            from test_sevenzip import _line as mk
            ename = "7z"
            line = lambda pw: mk(pw, b"stored payload for the sweep",
                                 salt=b"Qx", cycles=a)
        eng = get_engine(ename, device="jax")
        cpu = get_engine(ename, device="cpu")
        g3 = MaskGenerator("?l?l?l")
        plant = 7_077
        t0 = time.perf_counter()
        w = eng.make_mask_worker(g3, [cpu.parse_target(
            line(g3.candidate(plant)))], batch=min(B, 4096),
            hit_capacity=8, oracle=cpu)
        hits = w.process(WorkUnit(-1, plant - plant % w.stride,
                                  w.stride))
        compile_s = time.perf_counter() - t0
        ok = [(h.target_index, h.cand_index) for h in hits] == \
            [(0, plant)]

        g8 = MaskGenerator("?a?a?a?a?a?a?a?a")
        sweep = eng.make_mask_worker(g8, [cpu.parse_target(
            line(b"absent!9"))], batch=B, hit_capacity=64, oracle=cpu)
        hs, tested, dt, stride = timed_sweep(sweep, WorkUnit, 20.0)
        return {"case": name, "ok": ok, "param": a, "batch": stride,
                "worker": type(sweep).__name__,
                "compile_s": round(compile_s, 1),
                "hs": hs, "tested": tested,
                "elapsed_s": round(dt, 2),
                "hits": [h.cand_index for h in hits]}
    elif kind == "krb5aes":
        # krb5aes-<etype>-<logB>: the AES etype-17/18 TGS engine
        # through the PRODUCTION worker -- planted crack, then a timed
        # sweep.  Run once with DPRF_KRB5AES_KERNEL=0 (XLA PBKDF2) and
        # once =1 (fused Pallas KDF kernel, FIRST HARDWARE COMPILE --
        # schedule LAST in a session per TPU_PROBE_LOG_r05 finding 14).
        import hashlib as _hl
        import hmac as _hm
        import random as _rnd
        import sys as _sys
        _sys.path.insert(0, os.path.join(
            os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
            "tests"))
        from dprf_tpu import get_engine
        from dprf_tpu.engines.cpu.krb5aes import (USAGE_TGS_REP_TICKET,
                                                  cts_encrypt,
                                                  string_to_key,
                                                  usage_keys)
        from dprf_tpu.runtime.workunit import WorkUnit
        etype, logB = int(parts[1]), int(parts[2])
        B = 1 << logB
        kl = 16 if etype == 17 else 32

        def line(pw: bytes) -> str:
            rng = _rnd.Random(5)
            conf = bytes(rng.randrange(256) for _ in range(16))
            body = bytes([0x30, 0x82, 0x01, 0x80]) + \
                bytes(i % 256 for i in range(380))
            plain = conf + bytes([0x63, 0x82, 0x01, 0x84]) + body
            key = string_to_key(pw, b"REALM.TESTsvc", kl)
            ke, ki = usage_keys(key, USAGE_TGS_REP_TICKET)
            ed = cts_encrypt(ke, plain)
            chk = _hm.new(ki, plain, _hl.sha1).digest()[:12]
            return (f"$krb5tgs${etype}$svc$REALM.TEST${chk.hex()}$"
                    f"{ed.hex()}")

        eng = get_engine("krb5tgs-aes", device="jax")
        cpu = get_engine("krb5tgs-aes", device="cpu")
        g3 = MaskGenerator("?l?l?l")
        plant = 7_077
        t0 = time.perf_counter()
        w = eng.make_mask_worker(g3, [cpu.parse_target(
            line(g3.candidate(plant)))], batch=min(B, 4096),
            hit_capacity=8, oracle=cpu)
        hits = w.process(WorkUnit(-1, plant - plant % w.stride,
                                  w.stride))
        compile_s = time.perf_counter() - t0
        ok = [(h.target_index, h.cand_index) for h in hits] == \
            [(0, plant)]
        g8 = MaskGenerator("?a?a?a?a?a?a?a?a")
        sweep = eng.make_mask_worker(g8, [cpu.parse_target(
            line(b"absent!9"))], batch=B, hit_capacity=64, oracle=cpu)
        hs, tested, dt, stride = timed_sweep(sweep, WorkUnit, 20.0)
        return {"case": name, "ok": ok, "etype": etype,
                "batch": stride,
                "kernel_route": sorted(getattr(sweep, "kernel_targets",
                                               set())),
                "compile_s": round(compile_s, 1),
                "hs": hs, "tested": tested, "elapsed_s": round(dt, 2),
                "hits": [h.cand_index for h in hits]}
    elif kind == "krb5cfg":
        # krb5cfg-<logB>-<subc>-<unroll>: raw krb5 kernel throughput
        # at a (SUBC, unroll) point -- the tuning sweep behind the
        # production defaults.  Unmatchable target, hard_sync timing.
        from dprf_tpu.ops import pallas_krb5
        from dprf_tpu.utils.sync import hard_sync
        logB, subc, unroll = (int(x) for x in parts[1:])
        B = 1 << logB
        chunks = max(1, 2048 // subc)    # keep tile ~2048
        # unmatchable: impossible DER expectation via fake scalars
        step = pallas_krb5.make_krb5_crack_step(
            gen, B, sub=subc, chunks=chunks, unroll=bool(unroll))
        targs = (jnp.asarray([2], jnp.int32),
                 jnp.asarray([3, 5, 7, 9], jnp.int32),
                 jnp.asarray([0], jnp.int32),
                 jnp.asarray([-1], jnp.int32),
                 jnp.asarray([1], jnp.int32))
        t0 = time.perf_counter()
        hard_sync(step(base, jnp.int32(B), *targs))
        compile_s = time.perf_counter() - t0
        n, t0 = 0, time.perf_counter()
        while time.perf_counter() - t0 < 15.0:
            hard_sync(step(base, jnp.int32(B), *targs))
            n += 1
        dt = time.perf_counter() - t0
        return {"case": name, "ok": True, "batch": B, "subc": subc,
                "chunks": chunks, "unroll": bool(unroll),
                "hs": n * B / dt, "dispatches": n,
                "compile_s": round(compile_s, 1),
                "elapsed_s": round(dt, 2)}
    elif kind == "scanprobe":
        # scanprobe-<variant>-<inner>: minimal lax.scan shapes on this
        # backend, bisecting the round-4b config-stage hang (the
        # super-step scan program never came back from compile).
        #   scalar -- scalar carry, scalar ys
        #   ys     -- scalar carry, stacked [8,128] vector ys
        variant, inner = parts[1], int(parts[2])
        from jax import lax
        vec = jnp.arange(8 * 128, dtype=jnp.int32).reshape(8, 128)
        xs = jnp.arange(inner, dtype=jnp.int32)
        B = inner

        @jax.jit
        def run(b):
            def body(c, i):
                v = vec * (b[0] + i) + 1
                s = v.sum()
                y = s if variant == "scalar" else v
                return c + s, y
            acc, ys = lax.scan(body, jnp.int32(0), xs)
            return acc
    elif kind == "superstep":
        # superstep-<engine>-<logbatch>-<inner>: the production
        # worker super-dispatch path (ops/superstep.py scan wrapping
        # the real crack step) at a controllable batch, via
        # worker.process on one unit of exactly inner batches.
        ename, logB, inner = parts[1], int(parts[2]), int(parts[3])
        from dprf_tpu import get_engine
        from dprf_tpu.runtime.workunit import WorkUnit
        B = 1 << logB
        eng = get_engine(ename, device="jax")
        oracle = get_engine(ename, device="cpu")
        g8 = MaskGenerator("?l?l?l?l?l?l?l?l")
        from dprf_tpu.bench import _unmatchable
        tgt = oracle.parse_target(_unmatchable(oracle))
        worker = eng.make_mask_worker(g8, [tgt], batch=B,
                                      hit_capacity=64, oracle=oracle)
        worker.SUPER_CAP = inner
        worker.SUPER_MIN = min(worker.SUPER_MIN, inner)  # allow small
        unit_len = worker.stride * inner                 # bisect steps
        t0 = time.perf_counter()
        hits = worker.process(WorkUnit(-1, 0, unit_len))
        compile_s = time.perf_counter() - t0
        degraded = (getattr(worker, "_super_disabled", False)
                    or getattr(worker, "_wide_disabled", False))
        # a fused program must actually have been built -- a silent
        # fall-through to per-batch dispatch is a FAILED bisect case,
        # not a pass
        fused = bool(getattr(worker, "_super_cache", None)
                     or getattr(worker, "_wide_cache", None))
        k, t0 = 0, time.perf_counter()
        while True:
            worker.process(WorkUnit(-1, 0, unit_len))
            k += 1
            if time.perf_counter() - t0 > 20.0 or k >= 32:
                break
        dt = time.perf_counter() - t0
        return {"case": name, "ok": fused and not degraded,
                "degraded": degraded, "fused": fused,
                "mode": type(worker).SUPER_MODE,
                "worker": type(worker).__name__,
                "hs": k * unit_len / dt, "batch": B, "inner": inner,
                "units": k, "unit_s": round(dt / k, 2),
                "compile_s": round(compile_s, 1),
                "false_hits": len(hits)}
    else:
        raise ValueError(f"unknown case {name!r}")

    from dprf_tpu.utils.sync import hard_sync

    t0 = time.perf_counter()
    hard_sync(run(base))
    compile_s = time.perf_counter() - t0
    # time a few dispatches, at least one, up to ~30 s; hard_sync, not
    # block_until_ready, which returns at enqueue over the axon tunnel
    # (utils/sync.py) and would measure enqueue speed
    per = (B,)
    k, t0 = 0, time.perf_counter()
    while True:
        hard_sync(run(base))
        k += 1
        if time.perf_counter() - t0 > 30.0 or k >= 64:
            break
    dt = time.perf_counter() - t0
    return {"case": name, "ok": True, "hs": k * per[0] / dt,
            "batch": per[0], "dispatches": k,
            "dispatch_s": round(dt / k, 2),
            "compile_s": round(compile_s, 1)}


def main():
    name = sys.argv[1]
    emit({"case": name, "stage": "start", "t": time.time(),
          "pid": os.getpid()})
    try:
        doc = run_case(name)
    except Exception as e:
        doc = {"case": name, "ok": False,
               "error": f"{type(e).__name__}: {e}",
               "traceback": traceback.format_exc()[-1200:]}
    doc["t"] = time.time()
    emit(doc)
    print(json.dumps(doc)[:300])
    return 0 if doc.get("ok") else 1


if __name__ == "__main__":
    main()
