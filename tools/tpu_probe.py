"""Cooperative TPU-tunnel probe.

Attempts axon TPU init + one tiny computation and exits 0 on success.
NEVER kill this process externally: the one-client tunnel wedges when a
client dies mid-handshake (VERDICT.md r1, weakness 2).  Run it in the
background and read its status file instead.
"""
import json
import os
import sys
import time

STATUS = os.environ.get("TPU_PROBE_STATUS", "/tmp/tpu_probe_status.json")


def write(stage, **kw):
    # atomic replace: a poller must never read a truncated document
    tmp = STATUS + ".tmp"
    with open(tmp, "w") as f:
        json.dump({"stage": stage, "t": time.time(), **kw}, f)
        f.write("\n")
    os.replace(tmp, STATUS)


def main():
    write("starting", pid=os.getpid())
    import jax  # site registers the axon platform
    write("jax_imported")
    devs = jax.devices()  # may hang on a wedged tunnel
    write("devices", devices=[str(d) for d in devs],
          platform=devs[0].platform if devs else None)
    import jax.numpy as jnp
    x = jnp.arange(8 * 128, dtype=jnp.int32).reshape(8, 128)
    y = (x * 3 + 1).sum()
    val = int(y)
    write("compute_ok", value=val,
          expected=sum(i * 3 + 1 for i in range(8 * 128)))
    print("TPU probe OK:", devs)


if __name__ == "__main__":
    main()
