#!/usr/bin/env python
"""Per-worker device-idle report from an exported span stream.

The span-level assertion behind the pipelined worker loop (ISSUE 5):
for every worker, consecutive ``sweep`` spans should butt against (or
overlap) each other -- a positive inter-sweep gap is device idle, and
on a pipelined worker it must stay below the RPC round trip, because
sweep N+1 is already on the device stream while unit N's hits decode
and its complete report flies.  ``complete overlap`` counts sweeps
that started before the coordinator recorded the previous unit's
``complete`` span: proof the report RTT overlapped device work.

Usage::

    python tools/trace_overlap.py SESSION[.trace.jsonl]
        [--max-gap SECONDS]     # exit 1 if any worker idles longer
        [--json]                # machine-readable report on stdout

The analysis itself lives in dprf_tpu.telemetry.trace.overlap_report
so tests (tests/test_pipeline_rpc.py) assert on it directly.
"""

from __future__ import annotations

import argparse
import json
import os
import sys

sys.path.insert(0, os.path.dirname(
    os.path.dirname(os.path.abspath(__file__))))


def render(report: dict) -> str:
    rows = [("worker", "sweeps", "sweep_s", "idle_s", "max_gap_s",
             "overlapped", "complete_overlap")]
    for proc in sorted(report["workers"]):
        w = report["workers"][proc]
        rows.append((proc, str(w["sweeps"]), f"{w['sweep_s']:.3f}",
                     f"{w['idle_s']:.3f}", f"{w['max_gap_s']:.3f}",
                     f"{w['overlapped']}/{w['gaps']}",
                     f"{w['complete_overlaps']}/{w['gaps']}"))
    widths = [max(len(r[i]) for r in rows) for i in range(len(rows[0]))]
    return "\n".join("  ".join(c.ljust(w) for c, w in zip(r, widths))
                     for r in rows)


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        description="per-worker device-idle gaps between consecutive "
        "sweep spans of an exported trace")
    ap.add_argument("session", help="session journal path (or the "
                    ".trace.jsonl stream itself)")
    ap.add_argument("--max-gap", type=float, default=None, metavar="S",
                    help="fail (exit 1) if any worker's max inter-"
                    "sweep gap exceeds S seconds (e.g. the injected/"
                    "measured RPC round trip)")
    ap.add_argument("--json", action="store_true",
                    help="print the report as JSON instead of a table")
    args = ap.parse_args(argv)

    from dprf_tpu.telemetry.trace import (load_trace, overlap_report,
                                          trace_path)
    spans = load_trace(trace_path(args.session))
    if not spans:
        print(f"trace_overlap: no spans found at "
              f"{trace_path(args.session)}", file=sys.stderr)
        return 2
    report = overlap_report(spans)
    if not report["workers"]:
        print("trace_overlap: no sweep spans in the stream",
              file=sys.stderr)
        return 2
    if args.json:
        print(json.dumps(report, sort_keys=True))
    else:
        print(render(report))
    if args.max_gap is not None and report["max_gap_s"] > args.max_gap:
        print(f"trace_overlap: FAIL max inter-sweep gap "
              f"{report['max_gap_s']:.3f}s > {args.max_gap:.3f}s "
              "budget (device idle between units)", file=sys.stderr)
        return 1
    return 0


if __name__ == "__main__":
    sys.exit(main())
